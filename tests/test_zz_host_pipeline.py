"""Pipelined host-collective data path (late-alphabet; sequenced after
the tier-1 timeout horizon by design).

Covers the PR's tentpole: one-way PUSH_OOB segment frames, the
segmented double-buffered ring, the receive-buffer pool, the intra-host
hierarchy, the pipeline kill switch (`RAY_TPU_COLLECTIVE_PIPELINE=0`
must reproduce the legacy synchronous ring bit-for-bit), and the
fault-injection parity of the one-way send path (a dropped segment
surfaces as the op timeout, never a hang).

Knob plumbing: the collective config is read live from env in each
MEMBER process, so actors get a `configure` method that sets env vars
in their own process before joining (and between ops, to flip the
pipeline switch on a live group).
"""
import numpy as np
import pytest

SEG = 256   # collective_segment_bytes under test: tiny, so modest
            # arrays span many segments and boundaries are exercised

BASE_ENV = {
    "RAY_TPU_COLLECTIVE_SEGMENT_BYTES": SEG,
    "RAY_TPU_COLLECTIVE_PIPELINE": "1",
}


def _rank_cls(ray):
    @ray.remote
    class Rank:
        def configure(self, env):
            import os

            os.environ.update({k: str(v) for k, v in env.items()})
            return True

        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def allreduce(self, arr, name, op="sum"):
            from ray_tpu.util import collective as col

            return col.allreduce(arr, name, op=op)

        def reducescatter(self, arr, name, op="sum"):
            from ray_tpu.util import collective as col

            return col.reducescatter(arr, name, op=op)

        def allgather(self, arr, name):
            from ray_tpu.util import collective as col

            return col.allgather(arr, name)

        def chaos(self, seed, schedule):
            from ray_tpu._private import fault_injection as fi

            fi.install(seed, schedule)
            return True

        def chaos_off(self):
            from ray_tpu._private import fault_injection as fi

            fi.uninstall()
            return True

        def pool_stats(self):
            from ray_tpu._private.worker_runtime import COL_RECV_POOL

            return COL_RECV_POOL.stats()

        def store_stats(self):
            from ray_tpu._private.worker_runtime import current_worker

            return current_worker().store.stats()

        def segment_objects(self, name):
            """Store objects whose id carries this group's oid prefix —
            collective shm SEGMENTS only, invisible to the async frees
            of unrelated task-arg/result objects."""
            from ray_tpu._private.worker_runtime import (col_oid_prefix,
                                                         current_worker)

            prefix = col_oid_prefix(name)
            store = current_worker().store
            return sum(1 for oid, _ in store.list_objects()
                       if oid.startswith(prefix))

        def segment_provenance(self, name):
            """Full provenance for every still-live store object
            carrying this group's oid prefix: epoch + rank parsed from
            the segment id itself, plus the memory-anatomy leak sweep's
            orphan verdict (PR 18) — what a leak failure message names
            instead of a bare count."""
            from ray_tpu._private import memory_anatomy as _ma
            from ray_tpu._private.worker_runtime import (col_oid_prefix,
                                                         current_worker)

            _ma.sweep_local()
            prefix = col_oid_prefix(name)
            store = current_worker().store
            orphans = {r.get("oid"): r
                       for r in _ma.LEDGER.snapshot()["orphans"]}
            rows = []
            for oid, size in store.list_objects():
                if not oid.startswith(prefix):
                    continue
                _, epoch, rank = _ma.parse_col_oid(oid)
                verdict = orphans.get(oid.hex())
                rows.append({
                    "oid": oid.hex(), "nbytes": size, "group": name,
                    "epoch": epoch, "rank": rank,
                    "orphan_reason":
                        verdict.get("reason") if verdict else None})
            return rows

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

    return Rank


def _make_world(ray, world, name, env=None):
    Rank = _rank_cls(ray)
    actors = [Rank.options(num_cpus=0).remote() for _ in range(world)]
    merged = dict(BASE_ENV)
    merged.update(env or {})
    ray.get([a.configure.remote(merged) for a in actors])
    ray.get([a.join.remote(world, i, name)
             for i, a in enumerate(actors)], timeout=120)
    return actors


def _teardown(ray, actors, name):
    try:
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)
    except Exception:
        pass
    for a in actors:
        try:
            ray.kill(a)
        except Exception:
            pass


# ------------------------------------------------------------- pure units

def test_split_bounds_matches_array_split():
    from ray_tpu.util.collective.host_backend import _segments, \
        _split_bounds

    for total in (0, 1, 7, 31, 32, 33, 97, 1000):
        for parts in (1, 2, 3, 4, 7):
            flat = np.arange(total)
            ref = np.array_split(flat, parts)
            bounds = _split_bounds(total, parts)
            assert len(bounds) == parts
            for (lo, hi), chunk in zip(bounds, ref):
                assert np.array_equal(flat[lo:hi], chunk)
    # segment tiling covers [lo, hi) exactly, last segment ragged
    for lo, hi, step in ((0, 0, 4), (0, 1, 4), (3, 33, 8), (0, 32, 32),
                         (0, 33, 32), (0, 31, 32)):
        segs = _segments(lo, hi, step)
        if lo == hi:
            assert segs == []
            continue
        assert segs[0][0] == lo and segs[-1][1] == hi
        for (a, b), (c, d) in zip(segs, segs[1:]):
            assert b == c and b - a == step
        assert all(b > a for a, b in segs)


def test_hierarchy_plan_topology(ray_start_regular):
    """Planner logic over synthetic memberships (no network)."""
    import os

    from ray_tpu.util.collective.host_backend import HostGroup

    members = {0: ("10.0.0.1", 1), 1: ("10.0.0.1", 2),
               2: ("10.0.0.2", 3), 3: ("10.0.0.2", 4)}
    g = HostGroup("hier_plan", 4, 1, members)
    locals_, leaders = g._hierarchy_plan()
    assert locals_ == [0, 1] and leaders == [0, 2]
    # flat memberships don't plan a hierarchy in auto mode
    flat = {0: ("10.0.0.1", 1), 1: ("10.0.0.2", 2), 2: ("10.0.0.3", 3)}
    assert HostGroup("hier_flat", 3, 0, flat)._hierarchy_plan() is None
    one_host = {0: ("10.0.0.1", 1), 1: ("10.0.0.1", 2)}
    assert HostGroup("hier_one", 2, 0, one_host)._hierarchy_plan() is None
    # forced mode plans even on a single host (degenerate 1-leader ring)
    os.environ["RAY_TPU_COLLECTIVE_HIERARCHY"] = "1"
    try:
        locals_, leaders = \
            HostGroup("hier_f", 2, 1, one_host)._hierarchy_plan()
        assert locals_ == [0, 1] and leaders == [0]
    finally:
        os.environ.pop("RAY_TPU_COLLECTIVE_HIERARCHY", None)


def test_stale_member_addr_rebuilds_client(ray_start_regular):
    """A cached client whose peer address changed in `members` (group
    reincarnation) is dropped and rebuilt instead of winning until it
    errors."""
    from ray_tpu._private.protocol import RpcServer
    from ray_tpu.util.collective.host_backend import HostGroup

    class _H:
        pass

    s1 = RpcServer(_H()).start()
    s2 = RpcServer(_H()).start()
    from ray_tpu._private.worker_runtime import current_worker

    me = current_worker().addr
    g = HostGroup("stale_cli", 2, 0, {0: me, 1: s1.addr})
    try:
        c1 = g._client(1)
        assert tuple(c1.addr) == tuple(s1.addr)
        assert g._client(1) is c1          # cached while addr unchanged
        g.members[1] = tuple(s2.addr)      # reincarnated peer, new addr
        c2 = g._client(1)
        assert c2 is not c1
        assert tuple(c2.addr) == tuple(s2.addr)
        assert c1.closed
        # legacy (kill-switch) mode: the factory client is rebuilt ONCE
        # on the mode flip, then stays cached — even on builds where
        # the factory itself returns a PyRpcClient (flavor is judged by
        # the mode the client was built under, not isinstance)
        import os

        os.environ["RAY_TPU_COLLECTIVE_PIPELINE"] = "0"
        try:
            c3 = g._client(1)
            assert c3 is not c2
            assert g._client(1) is c3      # no churn in legacy mode
        finally:
            os.environ.pop("RAY_TPU_COLLECTIVE_PIPELINE", None)
    finally:
        g.close()
        s1.stop()
        s2.stop()


# --------------------------------------------------------------- oracles

SIZES = (0, 1, 7, 31, 32, 33, 63, 64, 65, 97, 256)
# elements; with SEG=256 these hit 0/1/exactly-one-segment/segment±1
# boundaries for both the 8-byte (seg_elems=32) and 4-byte
# (seg_elems=64) dtypes under test
DTYPES = ("float64", "float32", "int32")


def _mk(rank, size, dtype):
    rng = np.random.RandomState(1000 * rank + size)
    # integer-valued payloads: exact under any reduction order, so the
    # oracle comparison is equality for float dtypes too
    return rng.randint(0, 100, size=size).astype(dtype)


def test_pipelined_ring_oracle(ray_start_regular):
    ray = ray_start_regular
    for world in (2, 3):
        name = f"pipe_oracle_{world}"
        actors = _make_world(ray, world, name)
        try:
            for dtype in DTYPES:
                for size in SIZES:
                    ins = [_mk(r, size, dtype) for r in range(world)]
                    # integer-valued payloads: the sum is exact in every
                    # dtype, so equality holds under any reduce order
                    expect = ins[0].copy()
                    for a in ins[1:]:
                        expect = np.add(expect, a)
                    out = ray.get(
                        [a.allreduce.remote(ins[r], name)
                         for r, a in enumerate(actors)], timeout=60)
                    for got in out:
                        got = np.asarray(got)
                        assert got.dtype == np.dtype(dtype)
                        assert got.shape == (size,)
                        assert np.array_equal(got, expect)
                    rs = ray.get(
                        [a.reducescatter.remote(ins[r], name)
                         for r, a in enumerate(actors)], timeout=60)
                    shards = np.array_split(expect, world)
                    for r, got in enumerate(rs):
                        assert np.array_equal(np.asarray(got), shards[r])
            # allgather with rank-dependent shapes (whole-frame hops)
            gins = [np.arange(3 * r + 1, dtype=np.float32) + r
                    for r in range(world)]
            out = ray.get([a.allgather.remote(gins[r], name)
                           for r, a in enumerate(actors)], timeout=60)
            for got in out:
                assert len(got) == world
                for r in range(world):
                    assert np.array_equal(np.asarray(got[r]), gins[r])
            # max op (order-independent) over one awkward size
            ins = [_mk(r, 97, "float32") for r in range(world)]
            expect = np.maximum.reduce(ins)
            out = ray.get([a.allreduce.remote(ins[r], name, "max")
                           for r, a in enumerate(actors)], timeout=60)
            for got in out:
                assert np.array_equal(np.asarray(got), expect)
        finally:
            _teardown(ray, actors, name)


def test_pipeline_on_off_bit_identical(ray_start_regular):
    """The kill switch restores the legacy synchronous ring, and both
    paths produce bit-identical results on true random floats (same
    reduce operand order, just segment-wise)."""
    ray = ray_start_regular
    world, name = 3, "pipe_vs_legacy"
    actors = _make_world(ray, world, name)
    try:
        rng = np.random.RandomState(7)
        ins = [rng.standard_normal(517) for _ in range(world)]
        results = {}
        for mode in ("0", "1"):
            ray.get([a.configure.remote(
                {"RAY_TPU_COLLECTIVE_PIPELINE": mode}) for a in actors])
            ar = ray.get([a.allreduce.remote(ins[r], name)
                          for r, a in enumerate(actors)], timeout=60)
            rs = ray.get([a.reducescatter.remote(ins[r], name)
                          for r, a in enumerate(actors)], timeout=60)
            results[mode] = (ar, rs)
        for r in range(world):
            off_ar, on_ar = results["0"][0][r], results["1"][0][r]
            assert np.asarray(off_ar).tobytes() == \
                np.asarray(on_ar).tobytes()
            off_rs, on_rs = results["0"][1][r], results["1"][1][r]
            assert np.asarray(off_rs).tobytes() == \
                np.asarray(on_rs).tobytes()
    finally:
        _teardown(ray, actors, name)


def test_world1_shard_semantics(ray_start_regular):
    """world_size==1 reducescatter returns the rank's shard — the whole
    reduction with its original shape (consistent with allreduce's n==1
    behavior), not a flattened alias of the input."""
    ray = ray_start_regular
    name = "pipe_w1"
    actors = _make_world(ray, 1, name)
    try:
        arr = np.arange(12.0).reshape(3, 4)
        out = ray.get(actors[0].reducescatter.remote(arr, name),
                      timeout=30)
        got = np.asarray(out)
        assert got.shape == (3, 4)
        assert np.array_equal(got, arr)
        ar = np.asarray(ray.get(actors[0].allreduce.remote(arr, name),
                                timeout=30))
        assert ar.shape == (3, 4) and np.array_equal(ar, arr)
    finally:
        _teardown(ray, actors, name)


def test_hierarchy_forced_oracle(ray_start_regular):
    """Forced intra-host-first hierarchy (all ranks co-located → one
    leader, degenerate inter-host ring) matches the oracle."""
    ray = ray_start_regular
    world, name = 4, "pipe_hier"
    actors = _make_world(ray, world, name,
                         env={"RAY_TPU_COLLECTIVE_HIERARCHY": "1"})
    try:
        ins = [_mk(r, 300, "float64") for r in range(world)]
        expect = ins[0].copy()
        for a in ins[1:]:
            expect = np.add(expect, a)
        out = ray.get([a.allreduce.remote(ins[r], name)
                       for r, a in enumerate(actors)], timeout=60)
        for got in out:
            assert np.array_equal(np.asarray(got), expect)
    finally:
        _teardown(ray, actors, name)


def test_shm_segment_transport_oracle(ray_start_regular):
    """Segments over the 64 KB shm gate ride the node's object store
    (one copy in, zero-copy pinned view out). Oracle correctness at
    world 2 (pairwise exchange) and 3 (ring with shm-ref forwarding),
    plus a leak check: steady-state ops must not grow the store (every
    ephemeral segment object is deleted by its last consumer)."""
    ray = ray_start_regular
    for world in (2, 3):
        name = f"pipe_shm_{world}"
        actors = _make_world(
            ray, world, name,
            env={"RAY_TPU_COLLECTIVE_SEGMENT_BYTES": 128 * 1024})
        try:
            ins = [_mk(r, 100_000, "float64") for r in range(world)]
            expect = ins[0].copy()
            for a in ins[1:]:
                expect = np.add(expect, a)
            out = ray.get([a.allreduce.remote(ins[r], name)
                           for r, a in enumerate(actors)], timeout=60)
            for got in out:
                assert np.array_equal(np.asarray(got), expect)
            rs = ray.get([a.reducescatter.remote(ins[r], name)
                          for r, a in enumerate(actors)], timeout=60)
            shards = np.array_split(expect, world)
            for r, got in enumerate(rs):
                assert np.array_equal(np.asarray(got), shards[r])
            for _ in range(3):
                ray.get([a.allreduce.remote(ins[r], name)
                         for r, a in enumerate(actors)], timeout=60)
            # Leak check: count only objects carrying this group's oid
            # prefix (never the store TOTAL — unrelated task-arg frees
            # ride best-effort one-way pushes and land late under
            # full-suite load; the GCS now resends a failed free once,
            # RAY_TPU_STORE_FREE_RESEND, but late is still legal).
            # Every segment's last consumer deletes it synchronously
            # before its op returns, yet a rank whose op resolved FIRST
            # can be asked while a peer's final delete is microseconds
            # from landing — so poll briefly instead of asserting the
            # instantaneous count. A REAL leak outlives any deadline;
            # when one does, fail through the memory-anatomy plane
            # (PR 18) naming each segment's group/epoch/rank provenance
            # and the leak sweep's orphan verdict, not a bare count.
            import time as _time

            deadline = _time.time() + 20
            while True:
                leaked = ray.get(actors[0].segment_objects.remote(name),
                                 timeout=30)
                if leaked == 0:
                    break
                if _time.time() > deadline:
                    rows = ray.get(
                        actors[0].segment_provenance.remote(name),
                        timeout=30)
                    detail = "; ".join(
                        f"oid={r['oid'][:16]} group={r['group']} "
                        f"epoch={r['epoch']} rank={r['rank']} "
                        f"{r['nbytes']}B orphan={r['orphan_reason']}"
                        for r in rows) or "provenance unavailable"
                    raise AssertionError(
                        f"{leaked} shm segment objects leaked for "
                        f"group {name}: {detail}")
                _time.sleep(0.25)
        finally:
            _teardown(ray, actors, name)


def test_dropped_shm_notify_raises_timeout(ray_start_regular):
    """Chaos parity for the shm notify: a dropped col_push_shm strands
    the (already stored) segment; the op raises via the timeout
    detector and group destroy purges the stranded object."""
    ray = ray_start_regular
    world, name = 2, "pipe_shm_chaos"
    actors = _make_world(
        ray, world, name,
        env={"RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "3",
             "RAY_TPU_COLLECTIVE_SEGMENT_BYTES": 128 * 1024})
    try:
        ins = [_mk(r, 100_000, "float64") for r in range(world)]
        ray.get([a.allreduce.remote(ins[r], name)
                 for r, a in enumerate(actors)], timeout=60)
        import time as _time

        base = ray.get(actors[0].store_stats.remote(), timeout=30)
        ray.get([a.chaos.remote(0, "drop:*.col_push_shm:#1")
                 for a in actors])
        refs = [a.allreduce.remote(ins[r], name)
                for r, a in enumerate(actors)]
        with pytest.raises(Exception) as ei:
            ray.get(refs, timeout=60)
        assert "timed out" in str(ei.value).lower()
        ray.get([a.chaos_off.remote() for a in actors])
        # the dropped notify stranded a segment object (no mailbox ref
        # anywhere) — group destroy must sweep it via the group-tagged
        # oid prefix
        ray.get([a.destroy.remote(name) for a in actors], timeout=30)
        # 45s like the oracle test's settle poll: load-tolerant, never
        # leak-masking (a stranded segment would outlive any deadline)
        deadline = _time.time() + 45
        while True:
            after = ray.get(actors[0].store_stats.remote(), timeout=30)
            if after["num_objects"] <= base["num_objects"]:
                break
            if _time.time() > deadline:
                raise AssertionError(
                    f"stranded shm segment not reclaimed on destroy: "
                    f"{base} -> {after}")
            _time.sleep(0.25)
    finally:
        _teardown(ray, actors, name)


def test_duplicated_shm_notify_is_harmless(ray_start_regular):
    """A duplicate-delivered shm notify (fault plane `dup`) wraps the
    SAME store object in a second ColShmRef; the overwrite discard must
    NOT delete the object out from under the surviving ref (review
    finding: the op used to fail with 'segment vanished')."""
    ray = ray_start_regular
    world, name = 2, "pipe_shm_dup"
    actors = _make_world(
        ray, world, name,
        env={"RAY_TPU_COLLECTIVE_SEGMENT_BYTES": 128 * 1024})
    try:
        ins = [_mk(r, 100_000, "float64") for r in range(world)]
        expect = np.add(ins[0], ins[1])
        ray.get([a.chaos.remote(0, "dup:*.col_push_shm:p1")
                 for a in actors])
        for _ in range(2):
            out = ray.get([a.allreduce.remote(ins[r], name)
                           for r, a in enumerate(actors)], timeout=60)
            for got in out:
                assert np.array_equal(np.asarray(got), expect)
        ray.get([a.chaos_off.remote() for a in actors])
    finally:
        _teardown(ray, actors, name)


def test_dropped_segment_raises_timeout(ray_start_regular):
    """Fault-injection parity for the one-way path: a deterministically
    dropped col_push_frame makes the op raise via the timeout failure
    detector instead of hanging (one-way sends have no reply to fail)."""
    ray = ray_start_regular
    world, name = 2, "pipe_chaos"
    actors = _make_world(ray, world, name,
                         env={"RAY_TPU_COLLECTIVE_OP_TIMEOUT_S": "3"})
    try:
        # sanity: the group works before chaos
        ins = [_mk(r, 200, "float64") for r in range(world)]
        ray.get([a.allreduce.remote(ins[r], name)
                 for r, a in enumerate(actors)], timeout=60)
        ray.get([a.chaos.remote(0, "drop:*.col_push_frame:#2")
                 for a in actors])
        refs = [a.allreduce.remote(ins[r], name)
                for r, a in enumerate(actors)]
        with pytest.raises(Exception) as ei:
            ray.get(refs, timeout=60)
        assert "timed out" in str(ei.value).lower()
        ray.get([a.chaos_off.remote() for a in actors])
    finally:
        _teardown(ray, actors, name)


def test_pure_python_transport_and_pool():
    """The pure-Python transport's PUSH_OOB path receives segments into
    the per-(group, nbytes) pool and recycles buffers after release —
    steady-state ops allocate nothing per step. (The native C transport
    allocates in C; pooling applies to the Python fallback.)"""
    import os

    import ray_tpu

    os.environ["RAY_TPU_NATIVE_RPC"] = "0"
    try:
        ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
        name = "pipe_pypool"
        actors = _make_world(ray_tpu, 2, name)
        try:
            ins = [_mk(r, 2048, "float64") for r in range(2)]
            expect = np.add(ins[0], ins[1])
            for _ in range(3):   # repeat: steady-state reuse
                out = ray_tpu.get(
                    [a.allreduce.remote(ins[r], name)
                     for r, a in enumerate(actors)], timeout=60)
            for got in out:
                assert np.array_equal(np.asarray(got), expect)
            stats = ray_tpu.get(actors[0].pool_stats.remote(), timeout=30)
            assert stats["buffers"] > 0, \
                f"no pooled receive buffers after pipelined ops: {stats}"
        finally:
            _teardown(ray_tpu, actors, name)
    finally:
        os.environ.pop("RAY_TPU_NATIVE_RPC", None)
        ray_tpu.shutdown()


def test_push_parts_transport_roundtrip():
    """Transport-level PUSH_OOB: push_parts delivers (kwargs, frame) to
    the handler zero-copy, and the injected-drop path consults the
    fault plane exactly like call_async (satellite: chaos parity)."""
    import threading

    from ray_tpu._private import fault_injection as fi
    from ray_tpu._private import serialization as ser
    from ray_tpu._private.protocol import PyRpcClient, PyRpcServer

    got = []
    ev = threading.Event()

    class H:
        def rpc_blob(self, conn, key, frame):
            got.append((tuple(key), bytes(frame.view)))
            frame.release()
            ev.set()

    server = PyRpcServer(H()).start()
    client = PyRpcClient(server.addr, timeout=10)
    try:
        payload = np.arange(5000, dtype=np.float64)
        parts = ser.serialize_parts(payload)
        client.push_parts("blob", {"key": ("g", 1)}, parts, pool="g")
        assert ev.wait(10)
        key, raw = got[0]
        assert key == ("g", 1)
        val = ser.deserialize(raw)
        assert np.array_equal(val, payload)
        # fault plane: a deterministic drop means the frame never leaves
        inj = fi.install(0, "drop:*.blob:#1")
        try:
            ev.clear()
            client.push_parts("blob", {"key": ("g", 2)}, parts, pool="g")
            assert not ev.wait(0.5)
            assert ("drop", fi.get_role(), "blob", 1) in inj.trace()
        finally:
            fi.uninstall()
        # after chaos, delivery works again
        client.push_parts("blob", {"key": ("g", 3)}, parts, pool="g")
        assert ev.wait(10)
    finally:
        client.close()
        server.stop()
