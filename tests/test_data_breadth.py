"""Data breadth: RandomAccessDataset, to_tf, numpy/image/binary sources.

Reference tier: data/tests for random_access_dataset, to_tf, and the
numpy/image/binary datasources.
"""
import os

import numpy as np
import pytest


def test_read_numpy_round_trip(ray_start_regular, tmp_path):
    from ray_tpu import data

    a = np.arange(100, dtype=np.float32)
    b = np.arange(100, 200, dtype=np.float32)
    np.save(tmp_path / "a.npy", a)
    np.save(tmp_path / "b.npy", b)
    ds = data.read_numpy([str(tmp_path / "a.npy"),
                          str(tmp_path / "b.npy")])
    assert ds.count() == 200
    assert ds.num_blocks == 2
    out = ds.to_numpy()
    assert float(out.min()) == 0.0 and float(out.max()) == 199.0


def test_write_numpy_round_trip(ray_start_regular, tmp_path):
    from ray_tpu import data

    ds = data.from_numpy(np.arange(50, dtype=np.int32), parallelism=2)
    out_dir = str(tmp_path / "npys")
    files = ds.write_numpy(out_dir)
    assert len(files) == 2
    back = data.read_numpy(files)
    assert sorted(back.to_numpy().tolist()) == list(range(50))


def test_read_binary_files(ray_start_regular, tmp_path):
    from ray_tpu import data

    (tmp_path / "x.bin").write_bytes(b"\x00\x01\x02")
    (tmp_path / "y.bin").write_bytes(b"hello")
    ds = data.read_binary_files(
        [str(tmp_path / "x.bin"), str(tmp_path / "y.bin")],
        include_paths=True)
    rows = sorted(ds.take_all(), key=lambda r: r["path"])
    assert rows[0]["bytes"] == b"\x00\x01\x02"
    assert rows[1]["bytes"] == b"hello"
    assert rows[1]["path"].endswith("y.bin")


def test_read_images(ray_start_regular, tmp_path):
    from PIL import Image

    from ray_tpu import data

    for i, color in enumerate([(255, 0, 0), (0, 255, 0)]):
        Image.new("RGB", (8, 6), color).save(tmp_path / f"im{i}.png")
    ds = data.read_images(
        [str(tmp_path / "im0.png"), str(tmp_path / "im1.png")],
        size=(4, 4), include_paths=True)
    rows = sorted(ds.take_all(), key=lambda r: r["path"])
    assert rows[0]["image"].shape == (4, 4, 3)
    assert tuple(rows[0]["image"][0, 0]) == (255, 0, 0)
    assert tuple(rows[1]["image"][0, 0]) == (0, 255, 0)


def test_to_tf_features_and_labels(ray_start_regular):
    import tensorflow as tf

    from ray_tpu import data

    ds = data.from_items([{"x": float(i), "y": float(i * 2),
                           "label": i % 2} for i in range(64)],
                         parallelism=4)
    tfds = ds.to_tf(feature_columns=["x", "y"], label_columns="label",
                    batch_size=16)
    assert isinstance(tfds, tf.data.Dataset)
    total = 0
    for feats, label in tfds:
        assert set(feats.keys()) == {"x", "y"}
        assert feats["x"].shape[0] == label.shape[0]
        total += int(label.shape[0])
    assert total == 64

    # feature-dict-only mode
    tfds2 = ds.to_tf(batch_size=32)
    batch = next(iter(tfds2))
    assert set(batch.keys()) == {"x", "y", "label"}


def test_random_access_dataset(ray_start_regular):
    from ray_tpu import data

    ds = data.from_items([{"k": i, "v": i * 10}
                          for i in range(200)], parallelism=8)
    index = ds.to_random_access_dataset("k", num_workers=2)
    assert index.get(7) == {"k": 7, "v": 70}
    assert index.get(199) == {"k": 199, "v": 1990}
    assert index.get(500) is None
    got = index.multiget([3, 150, 42, 9999])
    assert got[0]["v"] == 30 and got[1]["v"] == 1500
    assert got[2]["v"] == 420 and got[3] is None
    # get_async returns a ref
    import ray_tpu

    assert ray_tpu.get(index.get_async(11)) == {"k": 11, "v": 110}
    stats = index.stats()
    assert sum(s["rows"] for s in stats) == 200 and len(stats) == 2
