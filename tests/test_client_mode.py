"""Client mode (`ray://`) tests — a separate OS process drives the cluster
through one proxy endpoint (reference test tier:
python/ray/tests/test_client.py, util/client/).
"""
import os
import signal
import subprocess
import sys
import time

import pytest

_SERVER_SCRIPT = """
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import ray_tpu
from ray_tpu.util.client import ClientServer

ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
server = ClientServer(port=0, host="127.0.0.1").start()
with open(sys.argv[1], "w") as f:
    f.write(str(server.addr[1]))
while True:
    time.sleep(1)
"""


@pytest.fixture
def client_server(tmp_path):
    port_file = tmp_path / "port"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_TESTING="1")
    log = open(tmp_path / "server.log", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-c", _SERVER_SCRIPT, str(port_file)], env=env,
        stdout=log, stderr=log)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if port_file.exists() and port_file.read_text().strip():
            break
        if proc.poll() is not None:
            raise RuntimeError("client server process died")
        time.sleep(0.1)
    else:
        proc.kill()
        raise TimeoutError("client server never came up")
    yield int(port_file.read_text())
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=10)


def test_client_mode_end_to_end(client_server):
    import ray_tpu

    ctx = ray_tpu.init(address=f"ray://127.0.0.1:{client_server}")
    try:
        # put/get round-trip
        ref = ray_tpu.put({"x": 41})
        assert ray_tpu.get(ref) == {"x": 41}

        # tasks, incl. passing a client-held ref as an argument
        @ray_tpu.remote
        def add(a, b):
            return a + b

        out = add.remote(1, ray_tpu.get(ref)["x"])
        assert ray_tpu.get(out) == 42
        chained = add.remote(out, 8)
        assert ray_tpu.get(chained) == 50

        # wait
        refs = [add.remote(i, i) for i in range(4)]
        ready, rest = ray_tpu.wait(refs, num_returns=4, timeout=30)
        assert len(ready) == 4 and not rest

        # actors through the proxy
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self, k=1):
                self.n += k
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote()) == 1
        assert ray_tpu.get(c.incr.remote(5)) == 6

        # named actor lookup via the gcs proxy
        named = Counter.options(name="client_counter").remote()
        ray_tpu.get(named.incr.remote())
        again = ray_tpu.get_actor("client_counter")
        assert ray_tpu.get(again.incr.remote()) == 2

        # cluster introspection routes through the proxy too
        assert ray_tpu.cluster_resources().get("CPU", 0) >= 4
        assert any(n["Alive"] for n in ray_tpu.nodes())
    finally:
        ray_tpu.shutdown()


def test_client_mode_errors_propagate(client_server):
    import ray_tpu

    ray_tpu.init(address=f"ray://127.0.0.1:{client_server}")
    try:
        @ray_tpu.remote
        def boom():
            raise ValueError("client-visible failure")

        with pytest.raises(Exception) as exc_info:
            ray_tpu.get(boom.remote(), timeout=60)
        assert "client-visible failure" in str(exc_info.value)
    finally:
        ray_tpu.shutdown()
