"""HF Transformers Trainer on the actor gang (reference:
train/huggingface/transformers). The model is built from a config (no
network), shrunk to CPU scale; the test proves HF's own train loop runs
data-parallel inside the gang and reports through the session."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


class _RandomLM(torch.utils.data.Dataset):
    def __init__(self, n=64, seq=16, vocab=64, seed=0):
        g = np.random.default_rng(seed)
        self.rows = g.integers(0, vocab, size=(n, seq), dtype=np.int64)

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, i):
        ids = torch.tensor(self.rows[i])
        return {"input_ids": ids, "labels": ids.clone()}


def _trainer_init(tmpdir):
    def init(config):
        from transformers import (
            GPT2Config,
            GPT2LMHeadModel,
            Trainer,
            TrainingArguments,
        )

        model = GPT2LMHeadModel(GPT2Config(
            n_layer=1, n_head=2, n_embd=32, vocab_size=64,
            n_positions=32))
        args = TrainingArguments(
            output_dir=str(tmpdir), per_device_train_batch_size=8,
            max_steps=4, logging_steps=2, report_to=[], use_cpu=True,
            save_strategy="steps", save_steps=4, save_total_limit=1,
            disable_tqdm=True, seed=0)
        return Trainer(model=model, args=args,
                       train_dataset=_RandomLM())

    return init


def test_transformers_trainer_on_gang(ray_start_regular, tmp_path):
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train.huggingface import TransformersTrainer

    result = TransformersTrainer(
        _trainer_init(tmp_path),
        scaling_config=ScalingConfig(num_workers=2),
    ).fit()
    assert result.metrics.get("done") is True
    assert result.metrics["global_step"] == 4
    assert result.metrics["training_loss"] > 0.0
    # HF's save streamed a checkpoint through the session (on_save hook)
    assert result.checkpoint is not None
    import os

    ckpt_dir = result.checkpoint.to_directory()
    assert any("model" in f or f.endswith(".json")
               for f in os.listdir(ckpt_dir)), os.listdir(ckpt_dir)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
