"""Runtime-env pip/py_modules cache tests (reference:
_private/runtime_env/pip.py + uri_cache.py). Unit tier exercises the
cache hermetically (no network — installs come from a local sdist);
the e2e tier (added with task wiring) runs a task inside the env."""
import os
import subprocess
import sys
import textwrap

import pytest


def _make_pkg(tmp_path, name="rtpu_demo_pkg", version="0.1"):
    """A minimal installable source tree (no network needed)."""
    pkg = tmp_path / name
    (pkg / name).mkdir(parents=True)
    (pkg / name / "__init__.py").write_text(
        f"MAGIC = 'demo-{version}'\n")
    (pkg / "setup.py").write_text(textwrap.dedent(f"""
        from setuptools import setup, find_packages
        setup(name={name!r}, version={version!r},
              packages=find_packages())
    """))
    return str(pkg)


def test_env_hash_stable_and_order_insensitive(tmp_path):
    from ray_tpu._private.runtime_env_pip import env_hash

    a = env_hash(["pkg-a", "pkg-b"], None)
    b = env_hash(["pkg-b", "pkg-a"], None)
    assert a == b and a.startswith("pipenv-")
    assert env_hash(["pkg-a"], None) != a
    assert env_hash(None, None) == env_hash([], [])


def test_pip_env_created_cached_and_importable(tmp_path):
    from ray_tpu._private.runtime_env_pip import PipEnvCache

    src = _make_pkg(tmp_path)
    cache = PipEnvCache(root=str(tmp_path / "envs"))
    info = cache.get_or_create(pip=[src])
    assert cache.creations == 1
    assert info["site_dirs"], info
    # importable via sys.path injection in a FRESH interpreter
    code = (f"import sys; sys.path[:0] = {info['site_dirs']!r}; "
            "import rtpu_demo_pkg; print(rtpu_demo_pkg.MAGIC)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "demo-0.1"

    # second request: cache hit, NO second install
    info2 = cache.get_or_create(pip=[src])
    assert cache.creations == 1
    assert info2["uri"] == info["uri"]

    # a second cache instance over the same root (another process's
    # view) also reuses the marker instead of reinstalling
    cache2 = PipEnvCache(root=str(tmp_path / "envs"))
    cache2.get_or_create(pip=[src])
    assert cache2.creations == 0


def test_py_modules_copied_onto_path(tmp_path):
    from ray_tpu._private.runtime_env_pip import PipEnvCache

    mod_dir = tmp_path / "mymod"
    mod_dir.mkdir()
    (mod_dir / "__init__.py").write_text("VALUE = 41\n")
    cache = PipEnvCache(root=str(tmp_path / "envs"))
    info = cache.get_or_create(py_modules=[str(mod_dir)])
    code = (f"import sys; sys.path[:0] = {info['site_dirs']!r}; "
            "import mymod; print(mymod.VALUE)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=30)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "41"


def test_eviction_spares_referenced_envs(tmp_path):
    from ray_tpu._private.runtime_env_pip import PipEnvCache

    cache = PipEnvCache(root=str(tmp_path / "envs"), max_cached=1)
    a = cache.get_or_create(py_modules=[])     # empty env a
    mod = tmp_path / "m2"
    mod.mkdir()
    (mod / "__init__.py").write_text("")
    cache.acquire(a["uri"])
    b = cache.get_or_create(py_modules=[str(mod)])
    cache.release(b["uri"])                    # triggers eviction pass
    # a is referenced -> survives; b is unreferenced and over budget
    root = str(tmp_path / "envs")
    alive = set(os.listdir(root))
    assert a["uri"] in alive



def test_task_runs_in_pip_env_and_cache_is_reused(tmp_path):
    """VERDICT #7 e2e: a task with runtime_env={"pip": [...]} imports the
    package; a second task reuses the cached env (no second install)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu

    src = _make_pkg(tmp_path, version="0.2")
    env_root = str(tmp_path / "envs")
    os.environ["RAY_TPU_RUNTIME_ENV_DIR"] = env_root
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote(runtime_env={"pip": [src],
                                     "env_vars": {"DEMO_FLAG": "42"}})
        def probe():
            import rtpu_demo_pkg

            return rtpu_demo_pkg.MAGIC, os.environ.get("DEMO_FLAG")

        assert ray_tpu.get(probe.remote(), timeout=120) == ("demo-0.2", "42")

        # a task WITHOUT the env must not see the package or the var
        @ray_tpu.remote
        def bare():
            try:
                import rtpu_demo_pkg  # noqa: F401

                return "leaked"
            except ImportError:
                return os.environ.get("DEMO_FLAG", "clean")

        assert ray_tpu.get(bare.remote(), timeout=60) == "clean"

        # cache reuse: the env dir's install marker must not change
        marker = next(
            os.path.join(env_root, d, "RAY_TPU_ENV_OK")
            for d in os.listdir(env_root) if d.startswith("pipenv-"))
        mtime = os.path.getmtime(marker)
        assert ray_tpu.get(probe.remote(), timeout=60)[0] == "demo-0.2"
        assert os.path.getmtime(marker) == mtime      # no reinstall
    finally:
        os.environ.pop("RAY_TPU_RUNTIME_ENV_DIR", None)
        ray_tpu.shutdown()


def test_actor_runtime_env_applied_at_creation(tmp_path):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu

    mod = tmp_path / "envmod"
    mod.mkdir()
    (mod / "__init__.py").write_text("WHO = 'actor-env'\n")
    os.environ["RAY_TPU_RUNTIME_ENV_DIR"] = str(tmp_path / "envs")
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
        class Holder:
            def __init__(self):
                import envmod

                self.who = envmod.WHO

            def who_am_i(self):
                return self.who

        h = Holder.remote()
        assert ray_tpu.get(h.who_am_i.remote(), timeout=60) == "actor-env"
    finally:
        os.environ.pop("RAY_TPU_RUNTIME_ENV_DIR", None)
        ray_tpu.shutdown()
if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v", "-x"]))
