"""Runtime-env pip/py_modules cache tests (reference:
_private/runtime_env/pip.py + uri_cache.py). Unit tier exercises the
cache hermetically (no network — installs come from a local sdist);
the e2e tier (added with task wiring) runs a task inside the env."""
import os
import subprocess
import sys
import textwrap

import pytest


def _make_pkg(tmp_path, name="rtpu_demo_pkg", version="0.1"):
    """A minimal installable source tree (no network needed)."""
    pkg = tmp_path / name
    (pkg / name).mkdir(parents=True)
    (pkg / name / "__init__.py").write_text(
        f"MAGIC = 'demo-{version}'\n")
    (pkg / "setup.py").write_text(textwrap.dedent(f"""
        from setuptools import setup, find_packages
        setup(name={name!r}, version={version!r},
              packages=find_packages())
    """))
    return str(pkg)


def test_env_hash_stable_and_order_insensitive(tmp_path):
    from ray_tpu._private.runtime_env_pip import env_hash

    a = env_hash(["pkg-a", "pkg-b"], None)
    b = env_hash(["pkg-b", "pkg-a"], None)
    assert a == b and a.startswith("pipenv-")
    assert env_hash(["pkg-a"], None) != a
    assert env_hash(None, None) == env_hash([], [])


def test_pip_env_created_cached_and_importable(tmp_path):
    from ray_tpu._private.runtime_env_pip import PipEnvCache

    src = _make_pkg(tmp_path)
    cache = PipEnvCache(root=str(tmp_path / "envs"))
    info = cache.get_or_create(pip=[src])
    assert cache.creations == 1
    assert info["site_dirs"], info
    # importable via sys.path injection in a FRESH interpreter
    code = (f"import sys; sys.path[:0] = {info['site_dirs']!r}; "
            "import rtpu_demo_pkg; print(rtpu_demo_pkg.MAGIC)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "demo-0.1"

    # second request: cache hit, NO second install
    info2 = cache.get_or_create(pip=[src])
    assert cache.creations == 1
    assert info2["uri"] == info["uri"]

    # a second cache instance over the same root (another process's
    # view) also reuses the marker instead of reinstalling
    cache2 = PipEnvCache(root=str(tmp_path / "envs"))
    cache2.get_or_create(pip=[src])
    assert cache2.creations == 0


def test_py_modules_copied_onto_path(tmp_path):
    from ray_tpu._private.runtime_env_pip import PipEnvCache

    mod_dir = tmp_path / "mymod"
    mod_dir.mkdir()
    (mod_dir / "__init__.py").write_text("VALUE = 41\n")
    cache = PipEnvCache(root=str(tmp_path / "envs"))
    info = cache.get_or_create(py_modules=[str(mod_dir)])
    code = (f"import sys; sys.path[:0] = {info['site_dirs']!r}; "
            "import mymod; print(mymod.VALUE)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=30)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "41"


def test_eviction_spares_referenced_envs(tmp_path):
    from ray_tpu._private.runtime_env_pip import PipEnvCache

    cache = PipEnvCache(root=str(tmp_path / "envs"), max_cached=1)
    a = cache.get_or_create(py_modules=[])     # empty env a
    mod = tmp_path / "m2"
    mod.mkdir()
    (mod / "__init__.py").write_text("")
    cache.acquire(a["uri"])
    b = cache.get_or_create(py_modules=[str(mod)])
    cache.release(b["uri"])                    # triggers eviction pass
    # a is referenced -> survives; b is unreferenced and over budget
    root = str(tmp_path / "envs")
    alive = set(os.listdir(root))
    assert a["uri"] in alive


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v", "-x"]))
