"""Autoscaler demand-binpacking tests (reference:
resource_demand_scheduler.py get_nodes_to_launch + the PG bundle
expansion at :171)."""
import pytest

from ray_tpu.autoscaler.resource_demand import (
    expand_pg_demand,
    get_nodes_to_launch,
    utilization_score,
)


CPU4 = {"resources": {"CPU": 4}}
TPU_HOST = {"resources": {"CPU": 8, "TPU": 4}}


def test_expand_strict_pack_sums_bundles():
    shapes = expand_pg_demand([{
        "strategy": "STRICT_PACK",
        "bundles": [{"CPU": 2}, {"CPU": 2, "TPU": 1}],
    }])
    assert shapes == [{"shape": {"CPU": 4, "TPU": 1},
                       "anti_affinity": None}]


def test_expand_strict_spread_tags_anti_affinity():
    shapes = expand_pg_demand([{
        "strategy": "STRICT_SPREAD", "pg_id": "g1",
        "bundles": [{"CPU": 1}, {"CPU": 1}, {"CPU": 1}],
    }])
    assert len(shapes) == 3
    assert all(s["anti_affinity"] == "g1" for s in shapes)


def test_headroom_absorbs_before_launch():
    plan, infeasible = get_nodes_to_launch(
        [{"CPU": 2}, {"CPU": 2}], [], headroom=[{"CPU": 4}],
        node_types={"cpu4": CPU4})
    assert plan == {} and infeasible == []


def test_binpacks_remaining_shapes_min_nodes():
    # 6 one-CPU shapes, 2 absorbed by headroom, 4 need exactly one cpu4
    plan, infeasible = get_nodes_to_launch(
        [{"CPU": 1}] * 6, [], headroom=[{"CPU": 2}],
        node_types={"cpu4": CPU4})
    assert plan == {"cpu4": 1} and infeasible == []


def test_strict_spread_needs_distinct_nodes():
    pg = {"strategy": "STRICT_SPREAD", "pg_id": "g",
          "bundles": [{"CPU": 1}] * 3}
    # one existing empty node can host ONE bundle; two more nodes needed
    plan, infeasible = get_nodes_to_launch(
        [], [pg], headroom=[{"CPU": 4}], node_types={"cpu4": CPU4})
    assert plan == {"cpu4": 2} and infeasible == []


def test_strict_pack_launches_one_covering_node():
    pg = {"strategy": "STRICT_PACK",
          "bundles": [{"CPU": 2, "TPU": 1}, {"CPU": 2, "TPU": 2}]}
    plan, infeasible = get_nodes_to_launch(
        [], [pg], headroom=[{"CPU": 4}],            # no TPU headroom
        node_types={"cpu4": CPU4, "tpu_host": TPU_HOST})
    assert plan == {"tpu_host": 1} and infeasible == []


def test_cpu_demand_avoids_tpu_nodes():
    plan, _ = get_nodes_to_launch(
        [{"CPU": 4}], [], headroom=[],
        node_types={"tpu_host": TPU_HOST, "cpu4": CPU4})
    assert plan == {"cpu4": 1}
    # but a TPU node is still used when it is the only feasible type
    plan, _ = get_nodes_to_launch(
        [{"CPU": 8}], [], headroom=[],
        node_types={"tpu_host": TPU_HOST, "cpu4": CPU4})
    assert plan == {"tpu_host": 1}


def test_infeasible_shape_reported_not_planned():
    plan, infeasible = get_nodes_to_launch(
        [{"CPU": 64}], [], headroom=[], node_types={"cpu4": CPU4})
    assert plan == {} and infeasible == [{"CPU": 64}]


def test_max_workers_and_per_type_caps():
    plan, infeasible = get_nodes_to_launch(
        [{"CPU": 4}] * 5, [], headroom=[],
        node_types={"cpu4": dict(CPU4, max_workers=2)},
        counts_by_type={"cpu4": 1}, max_workers=8)
    # per-type cap 2 with 1 existing -> only 1 more node, which absorbs
    # exactly one CPU:4 shape; the rest are unservable under the caps
    assert plan == {"cpu4": 1}
    assert len(infeasible) == 4


def test_tpu_slice_launched_as_unit():
    slice_type = {"resources": {"CPU": 8, "TPU": 4},
                  "tpu_slice": {"topology": "2x4", "hosts": 2}}
    pg = {"strategy": "STRICT_SPREAD", "pg_id": "ring",
          "bundles": [{"TPU": 4}, {"TPU": 4}]}
    plan, infeasible = get_nodes_to_launch(
        [], [pg], headroom=[], node_types={"v5e_2x4": slice_type})
    # ONE slice unit covers both anti-affinity bundles (2 hosts)
    assert plan == {"v5e_2x4": 1} and infeasible == []
    # max_workers and counts_by_type are in HOSTS: with 6 member hosts
    # (3 slices) already up, a 2-host slice cannot launch if only one
    # host slot remains in the budget
    plan, infeasible = get_nodes_to_launch(
        [], [pg], headroom=[], node_types={"v5e_2x4": slice_type},
        counts_by_type={"v5e_2x4": 6}, max_workers=7)
    assert plan == {} and len(infeasible) == 2


def test_utilization_prefers_tight_fit():
    big = {"CPU": 16}
    small = {"CPU": 4}
    shape = [{"CPU": 4}]
    assert utilization_score(small, shape) > utilization_score(big, shape)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v", "-x"]))
