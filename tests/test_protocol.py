"""Wire-protocol versioning + always-on spec validation.

The frame header's kind byte carries the protocol version in its high
nibble (protocol.py PROTOCOL_VERSION / rpc_core.cc kProtocolVersion);
a peer speaking a different revision must be rejected with a NAMED
error, never misparsed. Reference analog: protobuf schema versioning on
the gRPC control plane (/root/reference/src/ray/protobuf/).
"""
import pickle
import socket
import struct
import threading

import pytest

from ray_tpu._private import protocol
from ray_tpu._private.protocol import (
    PROTOCOL_VERSION, PyRpcClient, PyRpcServer, ProtocolMismatch, REPLY,
    _HDR,
)


def _bad_version_frame(kind: int, seq: int, payload) -> bytes:
    """A frame whose high nibble advertises a future protocol rev."""
    data = pickle.dumps(payload)
    bad_ver = (PROTOCOL_VERSION + 1) & 0x0F
    return _HDR.pack(len(data) + 9, (bad_ver << 4) | kind, seq) + data


def _bad_version_server():
    """Listener whose first connection gets a wrong-version REPLY to its
    first request; returns (listener, addr). Serves in a daemon thread."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)

    def serve_one():
        sock, _ = listener.accept()
        hdr = b""
        while len(hdr) < 17:
            chunk = sock.recv(17 - len(hdr))
            if not chunk:
                return
            hdr += chunk
        length, _, seq = _HDR.unpack(hdr)
        need = length - 9
        while need:
            chunk = sock.recv(need)
            if not chunk:
                return
            need -= len(chunk)
        try:
            sock.sendall(_bad_version_frame(REPLY, seq, "oops"))
            sock.recv(1)   # hold the conn until the client drops it
        except OSError:
            pass           # client tore the conn down — expected
        finally:
            sock.close()

    threading.Thread(target=serve_one, daemon=True).start()
    return listener, listener.getsockname()


class _EchoHandler:
    def rpc_echo(self, conn, x):
        return x


def test_python_roundtrip_carries_version():
    server = PyRpcServer(_EchoHandler()).start()
    try:
        client = PyRpcClient(server.addr)
        assert client.call("echo", x=41) == 41
        client.close()
    finally:
        server.stop()


def test_client_rejects_bad_version_reply():
    """A peer answering with a different wire rev fails the call with
    ProtocolMismatch (named), not a hang or a misparse."""
    listener, addr = _bad_version_server()
    client = PyRpcClient(addr)
    with pytest.raises(ProtocolMismatch, match="version mismatch"):
        client.call("echo", x=1, timeout=10)
    client.close()
    listener.close()


def test_server_drops_bad_version_client():
    """A client pushing frames from a different rev gets disconnected
    (the server cannot even parse its stream, so no in-band reply)."""
    server = PyRpcServer(_EchoHandler()).start()
    try:
        sock = socket.create_connection(server.addr, timeout=5)
        sock.sendall(_bad_version_frame(0, 1, ("echo", {"x": 1})))
        sock.settimeout(10)
        try:
            assert sock.recv(1) == b""   # clean FIN...
        except ConnectionResetError:
            pass                         # ...or an RST — both mean dropped
        sock.close()
    finally:
        server.stop()


def test_native_client_fails_cleanly_on_bad_version_peer():
    """The native (C++) client drops a wrong-version connection and the
    in-flight call raises the NAMED ProtocolMismatch, not a generic
    disconnect (rpc_cl_ver_mismatch plumbs the reason out of the C reader)."""
    pytest.importorskip("ray_tpu._private.native_rpc")
    from ray_tpu._private.native_rpc import load_lib, NativeRpcClient
    try:
        load_lib()
    except Exception:
        pytest.skip("native toolchain unavailable")

    listener, addr = _bad_version_server()
    client = NativeRpcClient(addr)
    with pytest.raises(ProtocolMismatch, match="version mismatch"):
        client.call("echo", x=1, timeout=10)
    client.close()
    listener.close()


def test_wire_format_pass_pins_cross_language_constants():
    """PR 8 satellite: the raylint wire-format pass parses BOTH
    languages — pin the constants the cluster actually ships so a
    one-sided bump (the PR 4/5 near-miss class) fails here, by name."""
    from ray_tpu._private.analysis import wire_format

    layout = wire_format.parse_layout()
    # v4: collective incarnation epochs (see protocol.py's history)
    assert layout["py"]["PROTOCOL_VERSION"] == 4
    assert layout["cc"]["kProtocolVersion"] == 4
    # PUSH_OOB (kind 3): the one-way out-of-band data-plane frame
    assert layout["py"]["PUSH_OOB"] == 3
    assert layout["cc"]["kPushOob"] == 3
    assert layout["py"]["PUSH_OOB"] == protocol.PUSH_OOB
    assert (layout["py"]["REQUEST"], layout["py"]["REPLY"],
            layout["py"]["PUSH"]) == (0, 1, 2)
    assert (layout["cc"]["kReq"], layout["cc"]["kReply"],
            layout["cc"]["kPush"]) == (0, 1, 2)
    # collective shm oid layout sums to the store's 16-byte id
    assert layout["id_size"] == 16
    # quantized-segment wire-dtype tags (PR 9): pinned values every
    # group member parses peers' segment headers by — renumbering is a
    # wire-protocol change
    assert layout["wire_tags"] == {"WIRE_OFF": 0, "WIRE_BF16": 1,
                                   "WIRE_INT8": 2}
    assert layout["wire_formats"] == {"bf16": "WIRE_BF16",
                                      "int8": "WIRE_INT8"}
    from ray_tpu.util.collective import wire as wire_mod

    assert (wire_mod.WIRE_OFF, wire_mod.WIRE_BF16,
            wire_mod.WIRE_INT8) == (0, 1, 2)
    # and the pass itself is clean over the real tree
    ctx = wire_format.AnalysisContext()
    assert list(wire_format.wire_format_pass(ctx)) == []


def test_wire_format_pass_fails_on_deleted_version_pin():
    """Acceptance: deleting the PROTOCOL_VERSION line from EITHER
    language makes the wire-format pass fail (exercised through the
    context's override hook; tests/test_zz_lint.py covers more tamper
    shapes)."""
    from ray_tpu._private.analysis import wire_format
    from ray_tpu._private.analysis.core import AnalysisContext

    for path, needle in ((wire_format.PROTOCOL_PY, "PROTOCOL_VERSION = "),
                         (wire_format.RPC_CC,
                          "constexpr int kProtocolVersion")):
        real = AnalysisContext().read_text(path)
        tampered = "\n".join(ln for ln in real.splitlines()
                             if needle not in ln)
        ctx = AnalysisContext(overrides={path: tampered})
        codes = {f.code for f in wire_format.wire_format_pass(ctx)}
        assert "RTW301" in codes, f"deleting {needle!r} from {path} " \
                                  f"did not fail the pass"


def test_wire_format_pass_fails_on_deleted_wire_tag():
    """PR 9: deleting (or colliding) a quantized-segment wire-dtype tag
    in util/collective/wire.py fails the pass with RTW305."""
    from ray_tpu._private.analysis import wire_format
    from ray_tpu._private.analysis.core import AnalysisContext

    real = AnalysisContext().read_text(wire_format.WIRE_PY)
    tag_line = next(ln for ln in real.splitlines()
                    if ln.startswith("WIRE_OFF"))
    # deleted tags
    ctx = AnalysisContext(overrides={
        wire_format.WIRE_PY: real.replace(tag_line, "")})
    codes = {f.code for f in wire_format.wire_format_pass(ctx)}
    assert "RTW305" in codes
    # colliding tags (two formats would parse each other's headers)
    ctx = AnalysisContext(overrides={
        wire_format.WIRE_PY: real.replace(
            tag_line, "WIRE_OFF, WIRE_BF16, WIRE_INT8 = 0, 1, 1")})
    findings = [f for f in wire_format.wire_format_pass(ctx)
                if f.code == "RTW305"]
    assert any("collide" in f.message for f in findings)


def test_spec_validation_always_on(monkeypatch):
    """validate_task_spec runs without any opt-in env var (round-5 fix:
    the schema is a contract, not a test aid)."""
    monkeypatch.delenv("RAY_TPU_VALIDATE_SPECS", raising=False)
    monkeypatch.delenv("RAY_TPU_TESTING", raising=False)
    from ray_tpu._private.task_spec import validate_task_spec
    with pytest.raises(ValueError, match="missing required keys"):
        validate_task_spec({"task_id": b"x" * 16})
    # explicit opt-OUT still works (bisecting the validator itself)
    monkeypatch.setenv("RAY_TPU_VALIDATE_SPECS", "0")
    validate_task_spec({"task_id": b"x" * 16})
