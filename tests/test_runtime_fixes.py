"""Regression tests: PG resource accounting, actor-init failure cleanup,
max_concurrency enforcement, distributed object release."""
import time

import numpy as np
import pytest


def test_pg_bundle_resources_reserved(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 3}], strategy="PACK")
    assert pg.wait(30)
    time.sleep(0.5)   # reservation rides the pubsub push
    raylet = ray._private.api._global_node.raylet
    assert raylet.resources_avail["CPU"] == pytest.approx(1.0), \
        "bundle resources must be deducted on the owning raylet"
    # and the raylet's GCS connection must still be healthy (no wedge)
    assert raylet._gcs.call("get_nodes", timeout=5.0)
    remove_placement_group(pg)
    time.sleep(0.5)
    assert raylet.resources_avail["CPU"] == pytest.approx(4.0)


def test_actor_init_failure_releases_resources(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_cpus=2)
    class Broken:
        def __init__(self):
            raise RuntimeError("init blows up")

        def ping(self):
            return "pong"

    for _ in range(3):    # would brick a 4-CPU node if reservations leaked
        a = Broken.remote()
        with pytest.raises(Exception):
            ray.get(a.ping.remote(), timeout=60)
    time.sleep(1.0)
    raylet = ray._private.api._global_node.raylet
    deadline = time.time() + 10
    while time.time() < deadline and \
            raylet.resources_avail.get("CPU", 0) < 4.0:
        time.sleep(0.2)
    assert raylet.resources_avail["CPU"] == pytest.approx(4.0)


def test_max_concurrency_serializes_cross_caller(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Unsafe:
        def __init__(self):
            self.active = 0
            self.max_active = 0

        def bump(self):
            self.active += 1
            self.max_active = max(self.max_active, self.active)
            time.sleep(0.05)
            self.active -= 1
            return self.max_active

    @ray.remote
    def caller(handle, n):
        import ray_tpu

        return ray_tpu.get([handle.bump.remote() for _ in range(n)])

    u = Unsafe.remote()
    # two separate worker processes hammer the same actor concurrently
    ray.get([caller.remote(u, 5), caller.remote(u, 5)], timeout=120)
    assert ray.get(u.bump.remote()) == 1, \
        "default max_concurrency=1 must serialize across callers"


def test_max_concurrency_allows_parallel(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(max_concurrency=4, num_cpus=0)
    class Gate:
        def __init__(self):
            self.count = 0

        def enter_and_wait(self):
            # all 4 callers must be inside simultaneously to return
            self.count += 1
            deadline = time.time() + 10
            while self.count < 4 and time.time() < deadline:
                time.sleep(0.01)
            return self.count >= 4

    g = Gate.remote()

    @ray.remote
    def hit(handle):
        import ray_tpu

        return ray_tpu.get(handle.enter_and_wait.remote())

    out = ray.get([hit.remote(g) for _ in range(4)], timeout=60)
    assert all(out), "max_concurrency=4 must admit 4 concurrent calls"


def test_object_freed_when_refs_dropped(ray_start_regular):
    ray = ray_start_regular
    worker = ray.get_runtime_context()._worker

    ref = ray.put(np.ones(200_000))     # big → shm store
    oid = ref.id
    assert worker.store.contains(oid)
    del ref
    import gc

    gc.collect()
    deadline = time.time() + 10
    while time.time() < deadline and worker.store.contains(oid):
        time.sleep(0.1)
    assert not worker.store.contains(oid), \
        "owner dropping the last ref must free the shm copy"


def test_object_not_freed_while_task_uses_it(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def consume(arr):
        time.sleep(1.0)
        return float(np.asarray(arr).sum())

    ref = ray.put(np.ones(200_000))
    out = consume.remote(ref)
    del ref          # drop owner ref while task in flight
    import gc

    gc.collect()
    assert ray.get(out, timeout=60) == 200_000.0


def test_leases_reclaimed_when_lessee_dies(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Leaker:
        def spawn_and_die(self):
            import os

            import ray_tpu

            @ray_tpu.remote
            def child():
                time.sleep(60)

            child.remote()      # acquires a lease from the raylet
            time.sleep(1.0)     # let the lease be granted
            os._exit(1)         # die without returning it

    a = Leaker.remote()
    try:
        ray.get(a.spawn_and_die.remote(), timeout=30)
    except Exception:
        pass
    raylet = ray._private.api._global_node.raylet
    deadline = time.time() + 20
    while time.time() < deadline and \
            raylet.resources_avail.get("CPU", 0) < 4.0:
        time.sleep(0.3)
    assert raylet.resources_avail["CPU"] == pytest.approx(4.0), \
        "leases of a dead lessee must be reclaimed"


def test_returned_exception_is_a_value(ray_start_regular):
    """A task that RETURNS an exception object yields it from get();
    only a task that RAISES re-raises (reference: only RayTaskError
    wrappers re-raise on the get path, _private/worker.py)."""
    ray = ray_start_regular

    @ray.remote
    def collect_err():
        return ValueError("collected, not raised")

    out = ray.get(collect_err.remote(), timeout=30)
    assert isinstance(out, ValueError)
    assert "collected" in str(out)

    @ray.remote
    def boom():
        raise ValueError("raised for real")

    with pytest.raises(Exception) as ei:
        ray.get(boom.remote(), timeout=30)
    assert "raised for real" in str(ei.value)


def test_returned_exception_in_list(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def maybe_fail(i):
        if i % 2:
            return RuntimeError(f"bad {i}")
        return i

    out = ray.get([maybe_fail.remote(i) for i in range(4)], timeout=30)
    assert out[0] == 0 and out[2] == 2
    assert isinstance(out[1], RuntimeError)
    assert isinstance(out[3], RuntimeError)


def test_fifo_semaphore_grant_order():
    """Slots are granted strictly in enqueue order."""
    import threading

    from ray_tpu._private.worker_runtime import FifoSemaphore

    sem = FifoSemaphore(1)
    order = []
    first = sem.enqueue()
    assert first is None  # immediate grant

    tickets = [sem.enqueue() for _ in range(3)]
    done = []

    def runner(idx, t):
        sem.wait(t)
        order.append(idx)
        sem.release()
        done.append(idx)

    threads = [threading.Thread(target=runner, args=(i, t))
               for i, t in enumerate(tickets)]
    # start in reverse to prove wakeup follows enqueue order, not start order
    for t in reversed(threads):
        t.start()
    time.sleep(0.2)
    sem.release()  # release the initial slot -> cascade
    for t in threads:
        t.join(timeout=10)
    assert order == [0, 1, 2]


def test_fifo_semaphore_cancel():
    from ray_tpu._private.worker_runtime import FifoSemaphore

    sem = FifoSemaphore(1)
    assert sem.enqueue() is None
    t1 = sem.enqueue()
    sem.cancel(t1)          # back out of the queue
    sem.release()           # slot free again
    assert sem.enqueue() is None  # would block if t1 leaked the slot
    sem.release()


def test_actor_ordering_survives_long_method(ray_start_regular):
    """A successor call never barges past a long-running predecessor
    (the old 60s wall-clock skip-ahead is gone; scaled-down probe)."""
    ray = ray_start_regular

    @ray.remote
    class Log:
        def __init__(self):
            self.calls = []

        def slow(self):
            time.sleep(3.0)
            self.calls.append("slow")
            return "slow"

        def fast(self):
            self.calls.append("fast")
            return "fast"

        def log(self):
            return self.calls

    a = Log.remote()
    r1 = a.slow.remote()
    r2 = a.fast.remote()
    assert ray.get([r1, r2], timeout=60) == ["slow", "fast"]
    assert ray.get(a.log.remote(), timeout=30) == ["slow", "fast"]


def test_ref_del_never_takes_locks(ray_start_regular):
    """GC-reentrancy regression (scalability-envelope deadlock):
    ObjectRef.__del__ fires _on_local_refs_zero, which the GC may run while
    THIS thread holds the memory-store lock or the worker lock. It must
    only enqueue — never lock — or the free path self-deadlocks."""
    import threading

    import ray_tpu
    from ray_tpu._private.worker_runtime import current_worker

    worker = current_worker()
    ref = ray_tpu.put(123)
    oid = ref.id
    # simulate __del__ firing while the allocating thread holds the store
    # lock (exactly where the envelope run deadlocked)
    acquired = worker.memory_store._lock.acquire()
    assert acquired
    try:
        done = threading.Event()

        def fire():
            worker._on_local_refs_zero(oid)
            done.set()

        t = threading.Thread(target=fire, daemon=True)
        t.start()
        assert done.wait(2.0), \
            "_on_local_refs_zero blocked while the store lock was held"
    finally:
        worker.memory_store._lock.release()
    # and with the lock released, the reaper eventually frees it
    import time

    deadline = time.time() + 10
    while time.time() < deadline:
        if worker.memory_store.get_nowait(oid) is None:
            break
        time.sleep(0.05)
    assert worker.memory_store.get_nowait(oid) is None
    del ref


def test_idle_workers_reaped_after_timeout():
    """worker_pool_idle_timeout_s: idle workers beyond the prestart
    watermark are returned to the OS (reference: worker_pool.h
    TryKillingIdleWorkers), instead of lingering forever."""
    import time

    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu._private import api

    ray_tpu.init(num_cpus=8,
                 system_config={"worker_pool_idle_timeout_s": 1.0})
    try:
        @ray_tpu.remote(num_cpus=0, max_retries=0)
        def noop(i):
            return i

        # a burst leases several workers; afterwards they go idle
        assert ray_tpu.get([noop.remote(i) for i in range(200)],
                           timeout=120) == list(range(200))
        raylet = api._global_node.raylet
        target = raylet._prestart_target
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with raylet._lock:
                idle = len(raylet._idle)
            if idle <= target:
                break
            time.sleep(0.5)
        assert idle <= target, \
            f"{idle} idle workers linger past the {target} watermark"
    finally:
        ray_tpu.shutdown()


def test_task_spec_schema_validation():
    """The spec schema is the contract: missing/undeclared keys fail at
    the producer when validation is on (RAY_TPU_TESTING does that for
    the whole suite — this exercises the failure modes directly)."""
    import os

    import pytest as _pytest

    from ray_tpu._private.task_spec import validate_task_spec

    good = {
        "task_id": os.urandom(16), "func_hash": b"h", "args": b"",
        "return_ids": [os.urandom(16)], "owner_addr": ("h", 1),
        "retries_left": 0, "task_desc": "t", "job_id": 0,
    }
    validate_task_spec(good)                      # passes
    validate_task_spec({**good, "_local": 1})     # local keys exempt
    with _pytest.raises(ValueError, match="missing required"):
        validate_task_spec({k: v for k, v in good.items()
                            if k != "func_hash"})
    with _pytest.raises(ValueError, match="undeclared keys"):
        validate_task_spec({**good, "surprise_field": 1})
    with _pytest.raises(ValueError, match="16 bytes"):
        validate_task_spec({**good, "task_id": b"short"})
