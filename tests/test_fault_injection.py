"""Message-level chaos: the deterministic RPC fault-injection plane.

Reference tier: python/ray/tests/test_chaos.py kills whole processes;
this suite injects faults one RPC at a time (drop / delay / duplicate /
disconnect / slow-reply, seeded + schedule-based —
ray_tpu/_private/fault_injection.py) and asserts that the unified
control-plane retry policy (_private/retry.py) turns every injected
fault into either an exact result or a fast, named failure — with the
retry counts bounded and the injected-fault sequence reproducible from
the RAY_TPU_FAULT_SEED + RAY_TPU_FAULT_SCHEDULE pair alone.

All schedules here are deterministic (%K / #i selectors or seeded
probabilities) and all injected delays are milliseconds — the suite
stays inside the tier-1 "not slow" budget by construction.
"""
import threading
import time

import pytest

from ray_tpu._private import fault_injection as fi
from ray_tpu._private.retry import (
    RetryBudget, RetryPolicy, is_retry_safe,
)

pytestmark = pytest.mark.fault_injection


@pytest.fixture(autouse=True)
def _clean_injector(monkeypatch):
    """No injector leaks across tests (the plane is process-global), and
    the exact retry-count assertions get a fresh process-wide budget so
    they can't flake on what earlier tests consumed."""
    from ray_tpu._private import retry

    monkeypatch.setattr(retry, "_default_budget",
                        retry.RetryBudget(capacity=1000,
                                          refill_per_s=1000))
    fi.uninstall()
    yield
    fi.uninstall()


# ---------------------------------------------------------------- unit tier


def test_schedule_parsing():
    rules = fi.parse_schedule(
        "drop:*.kv_put:p0.1;delay:gcs.*:%3:25;dup:*.echo:#1,4;"
        "slow_reply:raylet.get_nodes:p1.0:7")
    assert [r.action for r in rules] == ["drop", "delay", "dup",
                                        "slow_reply"]
    assert rules[0].prob == 0.1 and rules[0].method == "kv_put"
    assert rules[1].role == "gcs" and rules[1].every == 3
    assert rules[1].param_s == pytest.approx(0.025)
    assert rules[2].calls == frozenset({1, 4})
    assert rules[3].param_s == pytest.approx(0.007)


@pytest.mark.parametrize("bad", [
    "explode:*.x:p0.5",          # unknown action
    "drop:x:p0.5",               # scope missing the role.method dot
    "drop:*.x:p1.5",             # probability out of range
    "drop:*.x:%0",               # every-0th
    "drop:*.x:q9",               # unknown selector
    "drop:*.x",                  # missing selector
])
def test_schedule_rejects_malformed(bad):
    with pytest.raises(fi.ScheduleError):
        fi.parse_schedule(bad)


def test_decisions_deterministic_per_seed():
    """Same seed + schedule + per-method call sequence → identical event
    log, even when the calls interleave across threads."""
    schedule = "drop:*.a:p0.3;dup:*.b:p0.4;delay:*.*:%5:1"

    def drive(inj):
        threads = [
            threading.Thread(target=lambda: [inj.on_send("a")
                                             for _ in range(50)]),
            threading.Thread(target=lambda: [inj.on_send("b")
                                             for _ in range(50)]),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return inj.trace()

    t1 = drive(fi.FaultInjector(1234, schedule))
    t2 = drive(fi.FaultInjector(1234, schedule))
    t3 = drive(fi.FaultInjector(99, schedule))
    assert t1 == t2
    assert len(t1) > 0
    assert t1 != t3   # a different seed reshuffles the verdicts


def test_role_scoping():
    fi.set_role("gcs")
    try:
        inj = fi.FaultInjector(1, "drop:raylet.x:p1.0;dup:gcs.x:p1.0")
        plan = inj.on_send("x")
        assert plan.dup and not plan.drop   # raylet-scoped rule inert
    finally:
        fi.set_role("*")


def test_retry_policy_backoff_full_jitter():
    policy = RetryPolicy(base_backoff_s=0.1, max_backoff_s=0.4)
    for attempt in range(1, 8):
        cap = min(0.4, 0.1 * 2 ** (attempt - 1))
        for _ in range(20):
            b = policy.backoff(attempt)
            assert 0.0 <= b <= cap


def test_retry_policy_attempt_and_deadline_bounds():
    calls = []

    def flaky(timeout):
        calls.append(timeout)
        raise TimeoutError("nope")

    policy = RetryPolicy(max_attempts=3, base_backoff_s=0.001,
                         deadline_s=30.0, attempt_timeout_s=5.0)
    with pytest.raises(TimeoutError):
        policy.run(flaky, method="kv_get", retry_on=(TimeoutError,))
    assert len(calls) == 3                   # attempt cap honored
    assert all(t <= 5.0 for t in calls)      # per-attempt timeout shrunk

    calls.clear()
    policy = RetryPolicy(max_attempts=50, base_backoff_s=0.02,
                         deadline_s=0.15, attempt_timeout_s=5.0)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        policy.run(flaky, method="kv_get", retry_on=(TimeoutError,))
    assert time.monotonic() - t0 < 2.0       # deadline, not 50 attempts
    assert len(calls) < 50


def test_non_retry_safe_fails_fast():
    assert not is_retry_safe("actor_failed")
    assert not is_retry_safe("push_task")
    assert not is_retry_safe("some_future_method")  # unknown = fail fast
    assert is_retry_safe("kv_put") and is_retry_safe("request_worker_lease")

    calls = []

    def flaky(timeout):
        calls.append(1)
        raise TimeoutError("nope")

    with pytest.raises(TimeoutError):
        RetryPolicy(max_attempts=5, base_backoff_s=0.001).run(
            flaky, method="actor_failed", retry_on=(TimeoutError,))
    assert len(calls) == 1   # non-idempotent: one attempt, no blind retry


def test_retry_budget_bounds_amplification():
    budget = RetryBudget(capacity=3, refill_per_s=0.0)
    calls = []

    def flaky(timeout):
        calls.append(1)
        raise TimeoutError("nope")

    policy = RetryPolicy(max_attempts=100, base_backoff_s=0.0,
                         deadline_s=None, budget=budget)
    with pytest.raises(TimeoutError):
        policy.run(flaky, method="kv_get", retry_on=(TimeoutError,))
    assert len(calls) == 4   # 1 free attempt + 3 budgeted retries
    assert budget.exhausted_count == 1


# ----------------------------------------------------------- transport tier


class _EchoHandler:
    """Echo + a side-effect counter, so duplicate delivery is visible.
    rpc_ping mirrors rpc_echo under a RETRY-SAFE name (retry.py lists
    "ping") for the tests that exercise ReconnectingRpcClient healing."""

    def __init__(self):
        self.bumps = 0
        self.received: list = []
        self._lock = threading.Lock()

    def rpc_echo(self, conn, x):
        self.received.append(x)
        return x

    def rpc_ping(self, conn, x=None):
        return x

    def rpc_bump(self, conn):
        with self._lock:   # duplicate deliveries dispatch concurrently
            self.bumps += 1
            return self.bumps


@pytest.fixture(params=["py", "native"])
def echo_server(request, monkeypatch):
    """One echo server per transport; yields (handler, addr, client_fn)."""
    if request.param == "py":
        monkeypatch.setenv("RAY_TPU_NATIVE_RPC", "0")
    from ray_tpu._private import protocol

    # the transport choice is cached process-wide; reset around the test
    monkeypatch.setattr(protocol, "_native_state", [])
    handler = _EchoHandler()
    server = protocol.RpcServer(handler).start()
    if request.param == "native" and type(server).__name__ != \
            "NativeRpcServer":
        server.stop()
        pytest.skip("native rpc core not available")
    try:
        yield handler, server.addr
    finally:
        server.stop()


def test_drop_is_retried_to_exact_result(echo_server):
    """An injected request drop surfaces as a per-attempt timeout; the
    policy retries and the caller still gets the exact answer, with the
    retry count bounded and the fault on the event log."""
    from ray_tpu._private import protocol

    handler, addr = echo_server
    inj = fi.install(7, "drop:*.echo:#1")
    client = protocol.RpcClient(addr, timeout=30.0)
    try:
        policy = RetryPolicy(max_attempts=3, base_backoff_s=0.01,
                             attempt_timeout_s=0.3)
        attempts = []

        def call(timeout):
            attempts.append(timeout)
            return client.call("echo", x=41, timeout=timeout)

        assert policy.run(call, retry_on=(TimeoutError,)) == 41
        assert len(attempts) == 2               # drop + 1 retry, no more
        assert ("drop", fi.get_role(), "echo", 1) in inj.trace()
        assert handler.received == [41]         # server saw only the retry
    finally:
        client.close()


def test_duplicate_request_reaches_server_twice(echo_server):
    """dup sends the same seq twice: the server's handler runs twice
    (exercising idempotency), the caller sees ONE reply."""
    from ray_tpu._private import protocol

    handler, addr = echo_server
    fi.install(7, "dup:*.bump:#1")
    client = protocol.RpcClient(addr, timeout=5.0)
    try:
        result = client.call("bump")
        deadline = time.monotonic() + 2.0
        while handler.bumps < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert result in (1, 2)      # one reply, whichever landed first
        assert handler.bumps == 2    # both deliveries executed
    finally:
        client.close()


def test_delay_and_slow_reply_fire(echo_server):
    from ray_tpu._private import protocol

    _, addr = echo_server
    inj = fi.install(7, "delay:*.echo:#1:30;slow_reply:*.echo:#2:30")
    client = protocol.RpcClient(addr, timeout=5.0)
    try:
        t0 = time.monotonic()
        assert client.call("echo", x=1) == 1
        assert client.call("echo", x=2) == 2
        assert time.monotonic() - t0 >= 0.06   # both stalls happened
        actions = {e[0] for e in inj.trace()}
        assert actions == {"delay", "slow_reply"}
    finally:
        client.close()


def test_disconnect_heals_through_reconnecting_client(echo_server):
    """An injected disconnect kills the connection mid-workload; the
    self-healing client reconnects and the remaining calls succeed."""
    from ray_tpu._private import protocol

    _, addr = echo_server
    inj = fi.install(7, "disconnect:*.ping:#2")
    client = protocol.ReconnectingRpcClient(addr)
    try:
        assert [client.call("ping", x=i) for i in range(5)] == \
            [0, 1, 2, 3, 4]
        assert ("disconnect", fi.get_role(), "ping", 2) in inj.trace()
    finally:
        client.close()


def test_transport_workload_trace_reproducible(echo_server):
    """The acceptance bar: the same seed+schedule over the same workload
    yields the IDENTICAL injected-fault sequence, asserted on the event
    log across two full client/server runs."""
    from ray_tpu._private import protocol

    _, addr = echo_server
    schedule = ("drop:*.echo:p0.2;dup:*.bump:p0.3;"
                "delay:*.echo:p0.15:2;slow_reply:*.bump:p0.2:2")

    def run_once():
        inj = fi.install(4242, schedule)
        client = protocol.ReconnectingRpcClient(addr)
        policy = RetryPolicy(max_attempts=6, base_backoff_s=0.01,
                             attempt_timeout_s=1.0)
        try:
            for i in range(15):
                assert policy.run(
                    lambda t: client.call("echo", x=i, timeout=t),
                    retry_on=(TimeoutError, protocol.ConnectionLost)) == i
                client.call("bump", timeout=5.0)
        finally:
            client.close()
            fi.uninstall()
        # drops make extra (retried) echo sends: keep only each rule's
        # leading decisions, which both runs are guaranteed to reach
        return inj.trace()[:10], inj.event_count()

    trace1, n1 = run_once()
    trace2, n2 = run_once()
    assert n1 > 0
    assert trace1 == trace2


# ----------------------------------------------------- control-plane tiers


@pytest.fixture
def gcs_server():
    from ray_tpu._private.gcs import GcsServer

    gcs = GcsServer().start()
    try:
        yield gcs
    finally:
        gcs.stop()


def test_gcs_kv_exact_under_drop_delay_faults(gcs_server, monkeypatch):
    """≥5% drop + delay injected on the GCS KV plane: every put/get
    still returns the exact value, retry counts stay bounded, and the
    fault sequence is reproducible from the seed."""
    from ray_tpu._private import protocol

    monkeypatch.setenv("RAY_TPU_GCS_RPC_TIMEOUT_S", "0.5")
    monkeypatch.setenv("RAY_TPU_RPC_RETRY_BASE_BACKOFF_S", "0.01")
    received = []
    real_put = gcs_server.rpc_kv_put

    def counting_put(conn, **kw):
        received.append(kw["key"])
        return real_put(conn, **kw)

    monkeypatch.setattr(gcs_server, "rpc_kv_put", counting_put)
    inj = fi.install(
        11, "drop:*.kv_put:p0.12;drop:*.kv_get:p0.08;"
            "delay:*.kv_put:p0.2:5;delay:*.kv_get:p0.1:5")
    client = protocol.ReconnectingRpcClient(gcs_server.addr)
    n = 30
    try:
        for i in range(n):
            assert client.call("kv_put", ns="chaos",
                               key=f"k{i}".encode(),
                               value=f"v{i}".encode()) is True
        for i in range(n):
            assert client.call("kv_get", ns="chaos",
                               key=f"k{i}".encode()) == f"v{i}".encode()
    finally:
        client.close()
    drops = [e for e in inj.trace() if e[0] == "drop"]
    assert drops, "schedule injected no faults — selectors too narrow"
    # bounded retries: server-side receipts = sends that weren't dropped,
    # and sends <= n puts + one retry per dropped put (policy cap is 5)
    put_drops = sum(1 for e in drops if e[2] == "kv_put")
    assert n <= len(received) <= n + 4 * put_drops


def test_pubsub_redelivery_under_poll_faults(gcs_server, monkeypatch):
    """Dropped/slowed long-polls: the subscriber re-polls and every
    published message is still delivered exactly once, in order (acks
    ride after_seq, so lost polls redeliver rather than skip)."""
    from ray_tpu._private.protocol import RpcClient
    from ray_tpu._private.pubsub import Subscriber

    monkeypatch.setenv("RAY_TPU_GCS_RPC_TIMEOUT_S", "1.0")
    inj = fi.install(3, "drop:*.psub_poll:%4;slow_reply:*.psub_poll:%3:10")
    rpc = RpcClient(gcs_server.addr, timeout=5.0)
    got: list = []
    sub = Subscriber(rpc, poll_timeout=0.25)
    sub.subscribe("chaos-ch", got.append)
    try:
        # spread publishes across poll rounds so the stream straddles
        # the dropped/slowed polls instead of riding one lucky poll
        for i in range(20):
            gcs_server._publish("chaos-ch", {"n": i})
            time.sleep(0.06)
        deadline = time.monotonic() + 20
        while (len(got) < 20 or inj.event_count() == 0) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert [m["n"] for m in got] == list(range(20))
        assert inj.event_count() > 0
    finally:
        sub.stop()
        rpc.close()


def test_lease_grant_shape_validated_at_producer():
    """Satellite: a malformed lease grant/request fails AT the producer
    with the schema location in the message."""
    from ray_tpu._private.task_spec import (
        validate_lease_grant, validate_lease_request,
    )

    validate_lease_request({"CPU": 1.0}, {"spread": True})
    with pytest.raises(ValueError, match="task_spec"):
        validate_lease_request({"CPU": 1.0}, {"spraed": True})  # typo
    with pytest.raises(ValueError, match="number"):
        validate_lease_request({"CPU": "one"}, None)
    validate_lease_grant({"lease_id": "l", "worker_id": "w",
                          "worker_addr": ("h", 1), "node_id": "n"})
    with pytest.raises(ValueError, match="worker_addr"):
        validate_lease_grant({"lease_id": "l", "worker_id": "w",
                              "node_id": "n"})


def test_control_rpc_validation_at_client_boundary(gcs_server):
    """The GCS client boundary rejects a typo'd kv_put/register_actor
    before it crosses the wire."""
    from ray_tpu._private import protocol

    client = protocol.ReconnectingRpcClient(gcs_server.addr)
    try:
        with pytest.raises(ValueError, match="serialize"):
            client.call("kv_put", ns="x", key=b"k",
                        value={"not": "bytes"})
        with pytest.raises(ValueError, match="missing spec keys"):
            client.call("register_actor", actor_id=b"a" * 16,
                        spec={"class_name": "X"})
        with pytest.raises(ValueError, match="after_seq"):
            client.call("psub_poll", sub_id="s", after_seq=-3)
    finally:
        client.close()


def test_disabled_mode_overhead_is_one_none_check():
    """The acceptance criterion's microbench guard: with no injector
    installed, the per-call cost is a module-global load + None check.
    Generously bounded so it can never flake; the real sync-task
    microbench comparison rides ray_perf."""
    assert fi.ACTIVE is None
    t0 = time.perf_counter()
    for _ in range(200_000):
        inj = fi.ACTIVE
        if inj is not None:
            inj.on_send("echo")
    dt = time.perf_counter() - t0
    assert dt < 0.5   # ~0.01s in practice; 50x headroom


# ------------------------------------------------------------- cluster tier


def test_cluster_workload_exact_under_injected_faults(monkeypatch):
    """End to end on a real single-node runtime with ≥5% drop + delay on
    control-plane RPCs (driver in-process, workers via env inheritance):
    tasks, actor calls, put/get, and GCS KV all complete with exact
    results."""
    schedule = ("drop:*.kv_get:p0.05;drop:*.add_object_location:p0.05;"
                "drop:*.report_resources:p0.1;"
                "delay:*.kv_put:p0.25:5;delay:*.request_worker_lease:p0.3:8;"
                "slow_reply:*.get_nodes:p0.2:8")
    monkeypatch.setenv("RAY_TPU_FAULT_SEED", "2026")
    monkeypatch.setenv("RAY_TPU_FAULT_SCHEDULE", schedule)
    monkeypatch.setenv("RAY_TPU_GCS_RPC_TIMEOUT_S", "2.0")
    monkeypatch.setenv("RAY_TPU_RPC_RETRY_BASE_BACKOFF_S", "0.02")
    inj = fi.install(2026, schedule)   # driver side (env is for workers)
    import ray_tpu

    try:
        ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)

        @ray_tpu.remote(max_retries=3)
        def sq(i):
            return i * i

        @ray_tpu.remote(max_restarts=1, max_task_retries=3)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self, by):
                self.n += by
                return self.n

        # tasks
        assert ray_tpu.get([sq.remote(i) for i in range(12)],
                           timeout=120) == [i * i for i in range(12)]
        # actor calls (ordered per caller)
        c = Counter.remote()
        assert ray_tpu.get([c.bump.remote(2) for _ in range(6)],
                           timeout=120) == [2, 4, 6, 8, 10, 12]
        # put/get round trip
        refs = [ray_tpu.put(list(range(i))) for i in range(8)]
        assert ray_tpu.get(refs, timeout=120) == \
            [list(range(i)) for i in range(8)]
        # GCS KV through the retrying client
        from ray_tpu._private.worker_runtime import current_worker

        gcs = current_worker().gcs
        for i in range(10):
            gcs.call("kv_put", ns="chaos-e2e", key=f"k{i}".encode(),
                     value=f"v{i}".encode())
        assert [gcs.call("kv_get", ns="chaos-e2e", key=f"k{i}".encode())
                for i in range(10)] == \
            [f"v{i}".encode() for i in range(10)]
        assert inj.event_count() > 0, \
            "fault plane never fired — schedule/selectors inert"
    finally:
        ray_tpu.shutdown()
