"""Streaming data plane (ray_tpu/data/_internal/streaming/): bounded-
memory pull-based ingest, backpressure, locality-ordered prefetch,
device-put double buffering, task-side re-blocking, the collective
shuffle exchange, and the `RAY_TPU_DATA_STREAMING=0` kill switch.

Late-alphabet by design: the tier-1 duration guard keeps early files
fast; this whole suite stays well inside the per-file budget.
"""
import threading
import time

import numpy as np
import pytest


@pytest.fixture
def ds_env(ray_start_regular):
    yield ray_start_regular


def _collect(ds, **kw):
    return list(ds.iter_batches(**kw))


# ------------------------------------------------------------------ oracle


def test_bounded_memory_peak_le_budget(ds_env, monkeypatch):
    """Streaming a dataset 6x larger than the prefetch budget never
    holds more than `budget` blocks buffered/in flight at once."""
    from ray_tpu import data
    from ray_tpu.data._internal.streaming import last_executor

    monkeypatch.setenv("RAY_TPU_DATA_PREFETCH_BLOCKS", "2")
    ds = data.from_numpy(np.arange(12_000.0), parallelism=12)
    batches = _collect(ds, batch_size=1000)
    assert sum(len(b) for b in batches) == 12_000
    ex = last_executor()
    st = ex.stats()
    assert st["budget"] == 2
    assert st["peak_buffered_blocks"] <= 2, st
    assert st["consumed"] == 12


def test_backpressure_parks_producer(ds_env, monkeypatch):
    """A slow consumer stops the fetchers: while batch k is being
    'trained on', the executor never runs ahead of the budget window."""
    from ray_tpu import data
    from ray_tpu.data._internal.streaming import last_executor

    monkeypatch.setenv("RAY_TPU_DATA_PREFETCH_BLOCKS", "3")
    ds = data.from_numpy(np.arange(10_000.0), parallelism=10)
    it = ds.iter_batches(batch_size=1000)
    seen = 0
    for batch in it:
        seen += 1
        time.sleep(0.02)   # slow consumer
        ex = last_executor()
        st = ex.stats()
        # fetched-but-unconsumed work is bounded by the budget at every
        # step of the slow consumption, not just at the end
        assert st["peak_buffered_blocks"] <= 3, (seen, st)
    assert seen == 10


def test_streaming_equals_legacy_across_boundaries(ds_env, monkeypatch):
    """Batch contents are identical with streaming on vs off across
    block/batch-size boundaries, dict columns, and drop_last."""
    from ray_tpu import data

    plain = data.from_numpy(np.arange(500.0), parallelism=7)
    cols = data.from_items(
        [{"x": float(i), "y": i % 5} for i in range(300)], parallelism=4)

    def snap(ds, **kw):
        out = []
        for b in ds.iter_batches(**kw):
            if isinstance(b, dict):
                out.append({k: v.tobytes() for k, v in sorted(b.items())})
            else:
                out.append(b.tobytes())
        return out

    for ds, kwargs in [
        (plain, dict(batch_size=64)),
        (plain, dict(batch_size=64, drop_last=True)),
        (plain, dict(batch_size=1000)),       # one short batch
        (cols, dict(batch_size=77)),
    ]:
        monkeypatch.setenv("RAY_TPU_DATA_STREAMING", "1")
        on = snap(ds, **kwargs)
        monkeypatch.setenv("RAY_TPU_DATA_STREAMING", "0")
        off = snap(ds, **kwargs)
        assert on == off, kwargs


def test_kill_switch_legacy_path_runs(ds_env, monkeypatch):
    """RAY_TPU_DATA_STREAMING=0 really takes the legacy path (no
    streaming executor is constructed)."""
    from ray_tpu import data
    from ray_tpu.data._internal.streaming import executor as sx

    monkeypatch.setenv("RAY_TPU_DATA_STREAMING", "0")
    built = []
    orig = sx.StreamingExecutor.__init__

    def spy(self, *a, **kw):
        built.append(1)
        return orig(self, *a, **kw)

    monkeypatch.setattr(sx.StreamingExecutor, "__init__", spy)
    ds = data.from_numpy(np.arange(100.0), parallelism=4)
    assert sum(len(b) for b in ds.iter_batches(batch_size=32)) == 100
    assert not built


# ------------------------------------------------------- pipeline windows


def test_pipeline_carries_remainder_across_windows(ds_env):
    """70 rows in 10 blocks of 7, windows of 3 blocks (21 rows): the old
    per-window batching yielded a short batch at every window edge; now
    only the final batch may be short."""
    from ray_tpu import data

    pipe = data.from_numpy(np.arange(70.0), parallelism=10).window(
        blocks_per_window=3)
    sizes = [len(b) for b in pipe.iter_batches(batch_size=10)]
    assert sizes == [10] * 7
    # 75 rows: final remainder of 5 honors drop_last
    pipe = data.from_numpy(np.arange(75.0), parallelism=10).window(
        blocks_per_window=3)
    sizes = [len(b) for b in pipe.iter_batches(batch_size=10)]
    assert sizes == [10] * 7 + [5]
    pipe = data.from_numpy(np.arange(75.0), parallelism=10).window(
        blocks_per_window=3)
    sizes = [len(b)
             for b in pipe.iter_batches(batch_size=10, drop_last=True)]
    assert sizes == [10] * 7


def test_pipeline_streaming_equals_legacy(ds_env, monkeypatch):
    from ray_tpu import data

    def snap():
        pipe = data.from_numpy(np.arange(113.0), parallelism=6).window(
            blocks_per_window=2).map_batches(lambda a: a * 3)
        return [b.tobytes() for b in pipe.iter_batches(batch_size=25)]

    monkeypatch.setenv("RAY_TPU_DATA_STREAMING", "1")
    on = snap()
    monkeypatch.setenv("RAY_TPU_DATA_STREAMING", "0")
    off = snap()
    assert on == off and len(on) == 5


# ------------------------------------------------------------- locality


def test_locality_preference_orders_pulls(ds_env, monkeypatch):
    """Within the prefetch window, same-node blocks are pulled before
    remote ones; delivery order stays dataset order."""
    from ray_tpu.data._internal.streaming.executor import StreamingExecutor

    n = 8
    local = {0, 2, 4, 6}
    fetched = []

    class _FakeRef:
        def __init__(self, i):
            self.i = i

    ex = StreamingExecutor(iter([_FakeRef(i) for i in range(n)]),
                           budget=n, consumer="loctest", fetch_threads=1)
    monkeypatch.setattr(ex, "_is_local", lambda ref: ref.i in local)

    def fake_fetch(ref):
        fetched.append(ref.i)
        from ray_tpu.data._internal.streaming.executor import _Slot

        from ray_tpu._private import serialization as ser

        return _Slot(data=bytes(ser.serialize(ref.i))), (
            "local" if ref.i in local else "remote")

    monkeypatch.setattr(ex, "_fetch_one", fake_fetch)
    out = list(ex.iter_blocks())
    assert out == list(range(n))              # delivery: dataset order
    # pulls: all locals of the initial window before any remote
    first_half = fetched[: len(local)]
    assert set(first_half) == local, fetched
    st = ex.stats()
    assert st["blocks_local"] == 4 and st["blocks_remote"] == 4


def test_blocks_counted_local_on_single_node(ds_env):
    from ray_tpu import data
    from ray_tpu.data._internal.streaming import last_executor

    ds = data.from_numpy(np.arange(600.0), parallelism=6)
    list(ds.iter_batches(batch_size=100))
    st = last_executor().stats()
    assert st["blocks_local"] == 6 and st["blocks_remote"] == 0


# ------------------------------------------------------------- chaos


def test_dropped_block_fetch_retries_not_hang(ds_env):
    """A seeded chaos schedule dropping the first two block fetches is
    absorbed by the unified retry policy — iteration completes with the
    right rows and the injector trace shows the drops fired."""
    from ray_tpu import data
    from ray_tpu._private import fault_injection as fi

    ds = data.from_numpy(np.arange(200.0), parallelism=4)
    inj = fi.install(7, "drop:*.data_block_fetch:#1,2")
    try:
        t0 = time.monotonic()
        batches = list(ds.iter_batches(batch_size=50))
        elapsed = time.monotonic() - t0
    finally:
        fi.uninstall()
    assert sum(len(b) for b in batches) == 200
    np.testing.assert_array_equal(np.concatenate(batches),
                                  np.arange(200.0))
    drops = [e for e in inj.trace()
             if e[0] == "drop" and e[2] == "data_block_fetch"]
    assert len(drops) == 2, inj.trace()
    assert elapsed < 30, "retry path must not degenerate into a hang"


# -------------------------------------------------- task-side re-blocking


def test_reblock_ops_never_materialize_on_driver(ds_env, monkeypatch):
    """repartition / zip / uneven split re-block via remote tasks: the
    driver never calls take_all() mid-op."""
    from ray_tpu import data
    from ray_tpu.data.dataset import Dataset

    ds = data.from_numpy(np.arange(100.0), parallelism=4)
    other = data.from_items([f"s{i}" for i in range(100)], parallelism=4)

    def boom(self):
        raise AssertionError("driver-side take_all during re-block op")

    monkeypatch.setattr(Dataset, "take_all", boom)
    rep = ds.repartition(3)
    zipped = ds.zip(other)
    shards = ds.split(3)          # 4 blocks % 3 != 0 → uneven path
    monkeypatch.undo()

    assert rep.num_blocks == 3
    assert rep.take_all() == list(np.arange(100.0))
    rows = zipped.take_all()
    assert len(rows) == 100
    assert rows[5] == (5.0, "s5")
    got = sorted(float(v) for s in shards for v in s.take_all())
    assert got == list(np.arange(100.0))
    # legacy chunking: ceil(100/3)=34 → 34/34/32
    assert [len(s.take_all()) for s in shards] == [34, 34, 32]


def test_repartition_matches_legacy_content(ds_env):
    from ray_tpu import data

    rows = [{"a": float(i), "b": i % 7} for i in range(90)]
    ds = data.from_items(rows, parallelism=5).map(
        lambda r: {"a": r["a"] * 2, "b": r["b"]})
    rep = ds.repartition(4)
    assert rep.num_blocks == 4
    out = rep.take_all()
    assert [float(r["a"]) for r in out] == [i * 2.0 for i in range(90)]


# ------------------------------------------------------ collective shuffle


def test_collective_shuffle_matches_task_shuffle(ds_env, monkeypatch):
    """The all-to-all over the host-collective plane produces the exact
    rows of the task-based exchange for the same seed."""
    from ray_tpu import data

    ds = data.from_numpy(np.arange(80.0), parallelism=2)
    task_rows = ds.random_shuffle(seed=11).take_all()

    monkeypatch.setenv("RAY_TPU_DATA_SHUFFLE_COLLECTIVE", "1")
    col_rows = ds.random_shuffle(seed=11).take_all()
    assert col_rows == task_rows
    assert sorted(col_rows) == list(np.arange(80.0))
    assert col_rows != list(np.arange(80.0))


# ---------------------------------------------------------- device path


def test_device_put_double_buffered(ds_env):
    import jax

    from ray_tpu import data

    ds = data.from_numpy(np.arange(256.0), parallelism=4)
    batches = list(ds.iter_batches(batch_size=64, device_put=True))
    assert len(batches) == 4
    assert all(isinstance(b, jax.Array) for b in batches)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b) for b in batches]),
        np.arange(256.0))


# ----------------------------------------------------- staging + summary


def test_ephemeral_staging_roundtrip_and_cleanup(ds_env):
    """Heap-held fetched bytes re-stage into the shm store via
    put_ephemeral and are deleted at consume — no stranded staging
    objects afterwards."""
    from ray_tpu._private.worker_runtime import current_worker
    from ray_tpu.data._internal.streaming.executor import (
        _STAGE_PREFIX,
        StreamingExecutor,
    )

    w = current_worker()
    ex = StreamingExecutor(iter([]), consumer="stagetest")
    payload = b"z" * (64 * 1024)
    slot = ex._stage(w, payload)
    assert slot.pin is not None and slot.stage_id is not None
    assert bytes(slot.view()) == payload
    slot.release(w.store)
    strays = [oid for oid, _ in w.store.list_objects()
              if oid.startswith(_STAGE_PREFIX)]
    assert not strays


def test_summarize_data_and_wait_metric(ds_env):
    from ray_tpu import data
    from ray_tpu.experimental.state.api import summarize_data

    ds = data.from_numpy(np.arange(900.0), parallelism=3)
    ds._consumer = "zz-summary-test"
    n = len(list(ds.iter_batches(batch_size=100)))
    rows = {r["consumer"]: r
            for r in summarize_data()["consumers"]}
    row = rows.get("zz-summary-test")
    assert row is not None, rows
    assert row["batches"] == n == 9
    assert row["wait_total_s"] >= 0.0
    assert row["blocks_local"] == 3 and row["blocks_remote"] == 0


def test_train_shard_consumer_tagging(ds_env):
    """Train's dataset feed stamps per-rank consumer labels so data
    wait is attributable to the gang member it stalls."""
    from ray_tpu import data
    from ray_tpu.train.worker_group import TrainWorker

    tw = TrainWorker(world_rank=1, world_size=2)
    shard = data.from_numpy(np.arange(10.0), parallelism=1)
    tw.set_dataset_shard("train", shard)
    assert tw.session.dataset_shards["train"]._consumer == \
        "train/train/rank1"


def test_executor_close_releases_on_abandon(ds_env, monkeypatch):
    """Abandoning iteration mid-stream (take-style early exit) shuts the
    executor down and releases buffered slots."""
    from ray_tpu import data
    from ray_tpu.data._internal.streaming import last_executor

    monkeypatch.setenv("RAY_TPU_DATA_PREFETCH_BLOCKS", "4")
    ds = data.from_numpy(np.arange(5000.0), parallelism=10)
    it = ds.iter_batches(batch_size=500)
    next(it)
    it.close()
    ex = last_executor()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not ex._closed:
        time.sleep(0.01)
    assert ex._closed
    assert not ex._buffer
    # fetch threads drain promptly after close
    for t in ex._threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in ex._threads)
