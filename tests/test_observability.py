"""Observability: timeline spans, metrics registry + aggregation.

Reference tier: `ray timeline` (scripts.py:1757), ray.util.metrics
(util/metrics.py) → Prometheus text.
"""
import json
import time


def test_timeline_records_task_and_actor_spans(ray_start_regular, tmp_path):
    ray_tpu = ray_start_regular

    @ray_tpu.remote
    def work(ms):
        time.sleep(ms / 1000)
        return ms

    @ray_tpu.remote
    class Actor:
        def method(self):
            time.sleep(0.01)
            return 1

    assert ray_tpu.get([work.remote(5) for _ in range(3)]) == [5, 5, 5]
    a = Actor.remote()
    assert ray_tpu.get(a.method.remote()) == 1

    out = tmp_path / "trace.json"
    trace = ray_tpu.timeline(str(out))
    assert len(trace) >= 4
    cats = {e["cat"] for e in trace}
    assert "task" in cats and "actor_task" in cats
    names = [e["name"] for e in trace]
    assert any("work" in n for n in names)
    assert any("method" in n for n in names)
    for e in trace:
        assert e["ph"] == "X" and e["dur"] >= 0 and e["ts"] > 0
    # the file is valid chrome trace JSON
    loaded = json.loads(out.read_text())
    assert isinstance(loaded, list) and loaded


def test_metrics_counter_gauge_histogram(ray_start_regular):
    ray_tpu = ray_start_regular
    from ray_tpu.experimental.state.api import metrics_summary

    @ray_tpu.remote
    class Service:
        def __init__(self):
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            self.requests = Counter("svc_requests_total",
                                    description="requests handled",
                                    tag_keys=("route",))
            self.depth = Gauge("svc_queue_depth")
            self.latency = Histogram("svc_latency_s",
                                     boundaries=[0.01, 0.1, 1.0])

        def handle(self, route):
            self.requests.inc(1.0, tags={"route": route})
            self.depth.set(3)
            self.latency.observe(0.05)
            return True

    s = Service.remote()
    assert ray_tpu.get([s.handle.remote("/a"), s.handle.remote("/a"),
                        s.handle.remote("/b")]) == [True] * 3
    snaps = metrics_summary()
    by_name = {m["name"]: m for m in snaps}
    assert "svc_requests_total" in by_name
    vals = {tuple(sorted(v["tags"].items())): v["value"]
            for v in by_name["svc_requests_total"]["values"]}
    assert vals[(("route", "/a"),)] == 2.0
    assert vals[(("route", "/b"),)] == 1.0
    assert by_name["svc_queue_depth"]["values"][0]["value"] == 3.0
    text = metrics_summary(prometheus=True)
    assert "# TYPE svc_requests_total counter" in text
    assert 'svc_requests_total{route="/a"} 2.0' in text


def test_dump_stacks_collects_worker_threads(ray_start_regular):
    """`ray-tpu stack` analog: the raylet signals workers (faulthandler
    SIGUSR1) and collects per-thread Python stacks from their logs."""
    import ray_tpu
    from ray_tpu._private.worker_runtime import current_worker

    @ray_tpu.remote
    def warm():
        return 1

    assert ray_tpu.get(warm.remote(), timeout=60) == 1
    w = current_worker()
    dumps = w.raylet.call("dump_stacks", timeout=30.0)
    assert dumps, "no workers reported"
    joined = "\n".join(d["stack"] for d in dumps.values())
    # faulthandler's dump format: one 'Thread 0x...' header per thread,
    # with the worker main loop visible somewhere
    assert "Thread 0x" in joined or "Current thread" in joined, joined[:500]
    assert "serve_task_loop" in joined or "worker_main" in joined, \
        joined[:500]
