"""Long-poll pubsub tests (reference tier: src/ray/pubsub/ unit tests +
python gcs_pubsub tests)."""
import threading
import time

import pytest


def test_publisher_mailbox_and_ack():
    from ray_tpu._private.pubsub import Publisher

    pub = Publisher()
    sid = pub.subscribe(["a"])
    pub.publish("a", {"n": 1})
    pub.publish("b", {"n": 99})   # not subscribed
    pub.publish("a", {"n": 2})
    mail, max_seq = pub.poll(sid, after_seq=0, timeout=1)
    assert [m[2]["n"] for m in mail] == [1, 2]
    # unacked messages re-deliver; acked ones don't
    mail2, _ = pub.poll(sid, after_seq=mail[0][0], timeout=0.1)
    assert [m[2]["n"] for m in mail2] == [2]
    mail3, _ = pub.poll(sid, after_seq=max_seq, timeout=0.1)
    assert mail3 == []


def test_publisher_longpoll_blocks_until_publish():
    from ray_tpu._private.pubsub import Publisher

    pub = Publisher()
    sid = pub.subscribe(["ch"])
    got = {}

    def poller():
        got["mail"], got["seq"] = pub.poll(sid, 0, timeout=5)

    t = threading.Thread(target=poller)
    t.start()
    time.sleep(0.2)
    assert "mail" not in got          # parked
    pub.publish("ch", "wake")
    t.join(timeout=5)
    assert [m[2] for m in got["mail"]] == ["wake"]


def test_publisher_drop_oldest_overflow():
    from ray_tpu._private.pubsub import Publisher

    pub = Publisher(max_mailbox=5)
    sid = pub.subscribe(["x"])
    for i in range(12):
        pub.publish("x", i)
    mail, _ = pub.poll(sid, 0, timeout=0.1)
    assert [m[2] for m in mail] == [7, 8, 9, 10, 11]   # head dropped


def test_publisher_gc_stale_subscriber():
    from ray_tpu._private.pubsub import Publisher

    pub = Publisher(subscriber_timeout_s=0.1)
    sid = pub.subscribe(["x"])
    time.sleep(0.25)
    pub.publish("x", 1)               # GCs the stale subscriber
    with pytest.raises(KeyError):
        pub.poll(sid, 0, timeout=0.1)


def test_subscriber_over_rpc_and_gcs_channels(ray_start_regular):
    """End-to-end: a Subscriber long-polls the GCS and sees actor events."""
    import ray_tpu
    from ray_tpu._private.protocol import RpcClient
    from ray_tpu._private.pubsub import Subscriber
    from ray_tpu._private.worker_runtime import current_worker

    gcs_addr = current_worker().gcs.addr
    rpc = RpcClient(gcs_addr)
    events = []
    sub = Subscriber(rpc, poll_timeout=2.0)
    sub.subscribe("actors", events.append)

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if any(e.get("event") == "alive" for e in events):
            break
        time.sleep(0.1)
    assert any(e.get("event") == "alive" for e in events), events
    sub.stop()
    rpc.close()


def test_subscriber_gap_detection_after_publisher_gc():
    """Publisher-side GC drops the mailbox; the subscriber must surface
    the discontinuity instead of silently resuming (advisor, round 3)."""
    from ray_tpu._private.pubsub import Publisher, Subscriber

    pub = Publisher()

    class _LocalRpc:
        def call(self, method, **kw):
            kw.pop("timeout", None)
            if method == "psub_subscribe":
                return pub.rpc_psub_subscribe(None, kw["channels"],
                                              kw.get("sub_id"))
            if method == "psub_poll":
                return pub.rpc_psub_poll(None, kw["sub_id"],
                                         kw["after_seq"],
                                         kw.get("poll_timeout", 1))
            raise AssertionError(method)

    got, gaps = [], []
    sub = Subscriber(_LocalRpc(), poll_timeout=0.3, on_gap=gaps.append)
    sub.subscribe("ch", got.append)
    pub.publish("ch", "a")
    deadline = time.monotonic() + 10
    while "a" not in got and time.monotonic() < deadline:
        time.sleep(0.05)
    assert got == ["a"]

    # simulate publisher-side GC, then a publish the subscriber misses
    pub.unsubscribe(sub._sub_id)
    pub.publish("ch", "lost")
    deadline = time.monotonic() + 10
    while sub.gap_count == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert sub.gap_count == 1
    assert gaps and gaps[0] >= 1

    # stream continues after re-sync
    pub.publish("ch", "c")
    deadline = time.monotonic() + 10
    while "c" not in got and time.monotonic() < deadline:
        time.sleep(0.05)
    assert got == ["a", "c"]          # "lost" is gone, and reported as a gap
    sub.stop()


def test_subscriber_gap_on_mailbox_overflow():
    """Drop-oldest overflow at the publisher must surface as a gap, not a
    silently thinned stream (review finding, round 4)."""
    from ray_tpu._private.pubsub import Publisher, Subscriber

    pub = Publisher(max_mailbox=3)

    class _LocalRpc:
        def call(self, method, **kw):
            kw.pop("timeout", None)
            if method == "psub_subscribe":
                return pub.rpc_psub_subscribe(None, kw["channels"],
                                              kw.get("sub_id"))
            if method == "psub_poll":
                return pub.rpc_psub_poll(None, kw["sub_id"],
                                         kw["after_seq"],
                                         kw.get("poll_timeout", 1))
            raise AssertionError(method)

    got, gaps = [], []
    sub = Subscriber(_LocalRpc(), poll_timeout=0.2, on_gap=gaps.append)
    # register WITHOUT starting delivery yet: park the poll thread by
    # publishing a burst immediately, before the first poll drains
    sub_id = pub.subscribe(["ch"])
    sub._sub_id = sub_id
    for i in range(10):                       # 7 of these overflow out
        pub.publish("ch", i)
    sub.subscribe("ch", got.append)           # now start polling
    deadline = time.monotonic() + 10
    while len(got) < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert got == [7, 8, 9]
    deadline = time.monotonic() + 10
    while not gaps and time.monotonic() < deadline:
        time.sleep(0.05)
    assert sum(gaps) == 7, gaps               # every dropped message counted
    sub.stop()
