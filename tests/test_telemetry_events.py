"""Cluster event log + per-task latency breakdown (PR 2 tentpole).

Covers the acceptance criteria: `summarize_tasks()` returns a
queue/scheduling/execution breakdown for tasks run in-test;
`list_cluster_events()` shows the full state-transition sequence for a
failed-and-retried task under the PR 1 fault injector; the dashboard
serves `/api/events`; the CLI has an `events` subcommand.

NOTE: deliberately late-alphabet (test_telemetry_*) — the tier-1 870s
budget is wall-clock sensitive; keep these fast anyway.
"""
import json
import time

import pytest


def _subsequence(needle, haystack):
    """True if `needle` appears in `haystack` in order (gaps allowed)."""
    it = iter(haystack)
    return all(x in it for x in needle)


def test_summarize_tasks_latency_breakdown(ray_start_regular):
    ray_tpu = ray_start_regular
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def breakdown_sleepy(ms):
        time.sleep(ms / 1000)
        return ms

    assert ray_tpu.get([breakdown_sleepy.remote(30) for _ in range(3)],
                       timeout=120) == [30, 30, 30]
    summary = state.summarize_tasks()
    assert "tasks" in summary and "latency" in summary
    rows = [t for t in summary["tasks"]
            if t.get("desc") and "breakdown_sleepy" in t["desc"]]
    assert len(rows) == 3, summary["tasks"]
    for r in rows:
        assert r["state"] == "FINISHED"
        assert r["attempts"] >= 1
        # every phase present and sane for a completed task
        assert r["queue_s"] is not None and r["queue_s"] >= 0
        assert r["scheduling_s"] is not None and r["scheduling_s"] >= 0
        assert r["execution_s"] is not None and r["execution_s"] >= 0.02, r
    agg = next(v for k, v in summary["latency"].items()
               if "breakdown_sleepy" in k)
    assert agg["count"] == 3 and agg["finished"] == 3
    assert agg["execution_s"]["count"] == 3
    assert agg["execution_s"]["max"] >= agg["execution_s"]["mean"] > 0


def test_cluster_events_record_full_task_lifecycle(ray_start_regular):
    ray_tpu = ray_start_regular
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def lifecycle_probe():
        return 7

    assert ray_tpu.get(lifecycle_probe.remote(), timeout=120) == 7
    evs = state.list_cluster_events(
        filters=[("kind", "=", "task_state")])
    states = [e["state"] for e in evs
              if e.get("desc") and "lifecycle_probe" in e["desc"]]
    assert _subsequence(
        ["SUBMITTED", "LEASE_GRANTED", "RUNNING", "FINISHED"], states), \
        states
    # node registration is in the stream too (GCS-side events)
    node_evs = state.list_cluster_events(
        filters=[("kind", "=", "node_state")])
    assert any(e["state"] == "ALIVE" for e in node_evs), node_evs
    # limit keeps the recent TAIL of the time-ordered log, not the head
    tail = state.list_cluster_events(
        filters=[("kind", "=", "task_state")], limit=1)
    assert len(tail) == 1
    assert (tail[0]["node"], tail[0]["pid"], tail[0]["seq"]) == \
        (evs[-1]["node"], evs[-1]["pid"], evs[-1]["seq"])


@pytest.mark.fault_injection
def test_failed_and_retried_task_event_sequence(ray_start_regular):
    """Acceptance: the full state-transition sequence of a task whose
    first dispatch is killed by the PR 1 injector — and the injected
    fault itself — are visible in list_cluster_events()."""
    ray_tpu = ray_start_regular
    from ray_tpu._private import fault_injection
    from ray_tpu.experimental.state import api as state

    inj = fault_injection.install(7, "disconnect:*.push_task:#1")
    try:
        @ray_tpu.remote
        def flaky_probe():
            return 42

        assert ray_tpu.get(flaky_probe.remote(), timeout=120) == 42
        evs = state.list_cluster_events(
            filters=[("kind", "=", "task_state")])
        states = [e["state"] for e in evs
                  if e.get("desc") and "flaky_probe" in e["desc"]]
        assert _subsequence(
            ["SUBMITTED", "LEASE_GRANTED", "RESUBMITTED",
             "LEASE_GRANTED", "RUNNING", "FINISHED"], states), states
        faults = state.list_cluster_events(
            filters=[("kind", "=", "fault_injected")])
        ours = [e for e in faults if e["method"] == "push_task"
                and e["action"] == "disconnect"]
        n_injected = sum(1 for a, _r, m, _n in inj.trace()
                         if a == "disconnect" and m == "push_task")
        assert n_injected >= 1
        assert len(ours) == n_injected, (faults, inj.trace())
    finally:
        fault_injection.uninstall()


def test_actor_lifecycle_events(ray_start_regular):
    ray_tpu = ray_start_regular
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    class EventActor:
        def ping(self):
            return "pong"

    a = EventActor.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "pong"
    ray_tpu.kill(a, no_restart=True)
    states = []
    deadline = time.time() + 30
    while time.time() < deadline:
        evs = state.list_cluster_events(
            filters=[("kind", "=", "actor_state")])
        # resolve OUR actor's id via its REGISTERED event (the events
        # ring is process-global: other tests' actors may be in it)
        aid = next((e["actor_id"] for e in evs
                    if e["state"] == "REGISTERED"
                    and e.get("class_name") == "EventActor"), None)
        states = [e["state"] for e in evs if e.get("actor_id") == aid]
        if _subsequence(["REGISTERED", "ALIVE", "DEAD"], states):
            break
        time.sleep(0.2)
    assert _subsequence(["REGISTERED", "ALIVE", "DEAD"], states), states


def test_dashboard_events_and_metrics_routes(ray_start_regular):
    """`/api/events` serves the structured event stream and `/metrics`
    exposes the internal rpc-latency histograms (acceptance)."""
    ray_tpu = ray_start_regular
    from urllib.request import urlopen

    from ray_tpu.dashboard import DashboardServer

    @ray_tpu.remote
    def dash_probe():
        return 1

    assert ray_tpu.get(dash_probe.remote(), timeout=120) == 1
    server = DashboardServer(None, port=0).start()
    try:
        raw = urlopen(
            f"http://127.0.0.1:{server.port}/api/events",
            timeout=30).read()
        events = json.loads(raw)
        assert isinstance(events, list) and events
        kinds = {e["kind"] for e in events}
        assert "task_state" in kinds, kinds
        text = urlopen(
            f"http://127.0.0.1:{server.port}/metrics",
            timeout=30).read().decode()
        assert "# TYPE ray_tpu_rpc_latency_seconds histogram" in text
        assert "ray_tpu_rpc_latency_seconds_bucket" in text
        assert 'le="+Inf"' in text
    finally:
        server.stop()


def test_cli_events_subcommand(ray_start_regular, capsys):
    ray_tpu = ray_start_regular
    from ray_tpu.scripts import cli

    @ray_tpu.remote
    def cli_probe():
        return 1

    assert ray_tpu.get(cli_probe.remote(), timeout=120) == 1
    assert cli.main(["events", "--kind", "task_state", "--limit",
                     "500"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert isinstance(rows, list) and rows
    assert all(r["kind"] == "task_state" for r in rows)
    assert {"ts", "seq", "pid", "node", "state"} <= set(rows[0])
