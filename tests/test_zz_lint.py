"""raylint gate + framework unit tests (PR 8).

- the GATE: every pass family over the whole package must produce zero
  non-baselined findings and zero stale baseline entries, inside the
  acceptance wall-clock budget;
- framework semantics on synthetic fixture modules: known lock-order
  cycle, blocking-call-under-lock, user-callback-under-lock, guarded
  attribute written lock-free, timeout-less park, undeclared knob —
  asserting EXACT finding codes;
- suppression (`# raylint: disable=...`) and baseline mechanics;
- wire-format tamper proofs: deleting PROTOCOL_VERSION from either
  language (via the context's override hook — the real files are never
  touched) must fail the pass;
- the native sanitizer gate: `scripts/sanitize.sh --smoke` (slow,
  compiler-gated).

Late-alphabet on purpose (tier-1 wall-clock budget); keep fast.
"""
from __future__ import annotations

import textwrap
import time

import pytest

from ray_tpu._private import analysis
from ray_tpu._private.analysis import core as acore
from ray_tpu._private.analysis import knobs_pass, lock_discipline, wire_format

pytestmark = pytest.mark.lint


def _codes(findings):
    return {f.code for f in findings}


def _lock_codes(src: str):
    return _codes(lock_discipline.analyze_module_source(
        textwrap.dedent(src), "ray_tpu/_private/_zz_fixture.py"))


# ------------------------------------------------------------------- gate


def test_whole_package_zero_nonbaselined_findings():
    """THE acceptance gate: all four pass families over ray_tpu/, every
    finding either inline-suppressed or baselined, no stale baseline
    entries, inside the <20s budget."""
    t0 = time.monotonic()
    findings = analysis.run_all()
    elapsed = time.monotonic() - t0
    new, _known, stale = analysis.partition(findings)
    assert not new, "non-baselined raylint findings:\n" + "\n".join(
        f"  {f}" for f in new)
    assert not stale, (
        "stale baseline entries (the finding was fixed — delete the "
        f"line from analysis/baseline.txt): {stale}")
    assert elapsed < 20.0, f"raylint took {elapsed:.1f}s (budget 20s)"


def test_baseline_entries_all_have_justifications():
    """An unexplained baseline entry defeats the point of a baseline."""
    text = acore.BASELINE_PATH.read_text()
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        body, sep, comment = stripped.partition("#")
        assert sep and comment.strip(), \
            f"baseline entry lacks a justification comment: {stripped!r}"
        assert len(body.split()) == 3, \
            f"malformed baseline entry (want CODE path context): {stripped!r}"


# -------------------------------------------------- lock-discipline units


def test_blocking_call_under_lock_fixture():
    assert "RTL101" in _lock_codes("""
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)
    """)


def test_lock_order_cycle_fixture():
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert "RTL104" in codes


def test_no_cycle_for_consistent_order():
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ab2(self):
                with self._a:
                    with self._b:
                        pass
    """)
    assert "RTL104" not in codes


def test_cross_method_lock_cycle_via_self_call():
    """One self.method() hop: m holds A and calls n, which takes B;
    p holds B and calls q, which takes A — a cycle no single method
    shows."""
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m(self):
                with self._a:
                    self.n()

            def n(self):
                with self._b:
                    pass

            def p(self):
                with self._b:
                    self.q()

            def q(self):
                with self._a:
                    pass
    """)
    assert "RTL104" in codes


def test_user_callback_under_lock_fixture():
    assert "RTL103" in _lock_codes("""
        import threading

        _lock = threading.Lock()

        def cached(key, loader):
            with _lock:
                return loader()
    """)


def test_guarded_attr_written_lockfree_fixture():
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = None

            def update(self, v):
                with self._lock:
                    if self._state is None:
                        self._state = v

            def racy_reset(self):
                self._state = None
    """)
    assert "RTL105" in codes


def test_timeout_less_park_fixture():
    assert "RTL102" in _lock_codes("""
        class C:
            def run(self, q):
                return q.get()
    """)


def _growth_codes(src: str, path: str = "ray_tpu/_private/gcs.py"):
    return _codes(lock_discipline.analyze_growth_source(
        textwrap.dedent(src), path))


def test_unbounded_growth_fixture():
    """RTL106: a per-id table grown on registration with no removal on
    any path — the leak class the 100-node soak finds one field at a
    time."""
    src = """
        class ControlTable:
            def __init__(self):
                self._by_node = {}
                self._watchers = set()

            def register(self, node_id, info):
                self._by_node[node_id] = info

            def watch(self, sub_id):
                self._watchers.add(sub_id)
    """
    codes = _growth_codes(src)
    assert codes == {"RTL106"}
    contexts = {f.context for f in lock_discipline.analyze_growth_source(
        textwrap.dedent(src), "ray_tpu/_private/gcs.py")}
    assert contexts == {"ControlTable._by_node", "ControlTable._watchers"}


def test_growth_with_removal_on_death_path_is_clean():
    assert _growth_codes("""
        class ControlTable:
            def __init__(self):
                self._by_node = {}
                self._watchers = set()

            def register(self, node_id, info):
                self._by_node[node_id] = info

            def watch(self, sub_id):
                self._watchers.add(sub_id)

            def on_node_dead(self, node_id):
                self._by_node.pop(node_id, None)

            def unwatch(self, sub_id):
                self._watchers.discard(sub_id)
    """) == set()


def test_growth_exemptions_fixture():
    """Bounded deques, constant-key stats dicts, swap-and-flush
    reassignment, and receiver-CHAIN shrinks are all clean; files
    outside the control-plane set are out of scope entirely."""
    src = """
        import collections

        class C:
            def __init__(self):
                self._ring = collections.deque(maxlen=64)
                self._stats = {"a": 0}
                self._pending = {}
                self._nested = {}

            def record(self, x):
                self._ring.append(x)
                self._stats["a"] = 1

            def enqueue(self, k, v):
                self._pending[k] = v
                self._nested.setdefault(k, {})[v] = 1

            def flush(self):
                out, self._pending = self._pending, {}
                return out

            def drop(self, k):
                self._nested.get(k, {}).pop(k, None)
    """
    assert _growth_codes(src) == set()
    # a leaky class OUTSIDE the control-plane module set is not flagged
    leaky = """
        class C:
            def __init__(self):
                self._t = {}
            def put(self, k, v):
                self._t[k] = v
    """
    assert _growth_codes(leaky, "ray_tpu/serve/_private/router.py") == set()
    assert _growth_codes(leaky) == {"RTL106"}


def test_condition_wait_under_its_own_lock_is_clean():
    """Condition.wait RELEASES the lock — the canonical pattern must
    not be flagged as blocking-under-lock."""
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._cond = threading.Condition()

            def take(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait(1.0)
    """)
    assert "RTL101" not in codes


def test_condition_notify_without_lock_fixture():
    """RTL107 (async-collective issue-thread discipline): notify on an
    unheld Condition raises at runtime; wait outside the lock races its
    own predicate. Both must be findings; the held variants must not."""
    codes = _lock_codes("""
        import threading

        class Handle:
            def __init__(self):
                self._cond = threading.Condition()
                self._done = False

            def bad_finish(self):
                self._done = True
                self._cond.notify_all()      # not held: RuntimeError

            def bad_wait(self):
                self._cond.wait_for(lambda: self._done, timeout=5.0)
    """)
    assert "RTL107" in codes
    clean = _lock_codes("""
        import threading

        class Handle:
            def __init__(self):
                self._cond = threading.Condition()
                self._done = False

            def finish(self):
                with self._cond:
                    self._done = True
                    self._cond.notify_all()

            def wait(self):
                with self._cond:
                    self._cond.wait_for(lambda: self._done, timeout=5.0)
    """)
    assert "RTL107" not in clean


def test_condition_notify_in_locked_method_not_flagged():
    """*_locked methods run with the CALLER's lock held; name-based
    identity can't prove which, so RTL107 stays quiet there."""
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._cond = threading.Condition()

            def _finish_locked(self):
                self._cond.notify_all()
    """)
    assert "RTL107" not in codes


def test_condition_alias_from_ctor_param_covered():
    """RTL107 coverage extension (PR 19): a Condition RECEIVED as a
    ctor parameter and stored under a non-lockish attribute name (the
    async-handle pattern — an issue queue hands its completion
    Condition to every handle it mints) is still a lock token: lock
    identity propagates from the aliased parameter name, so notify on
    it unheld is a finding and the held variant stays clean."""
    codes = _lock_codes("""
        import threading

        class Handle:
            def __init__(self, cond):
                self._completion = cond
                self._done = False

            def bad_finish(self):
                self._done = True
                self._completion.notify_all()    # not held
    """)
    assert "RTL107" in codes
    clean = _lock_codes("""
        import threading

        class Handle:
            def __init__(self, cond):
                self._completion = cond
                self._done = False

            def finish(self):
                with self._completion:
                    self._done = True
                    self._completion.notify_all()
    """)
    assert "RTL107" not in clean


def test_nested_function_runs_lock_free():
    """A closure defined under a lock runs LATER (its own thread) —
    its blocking calls are not under-the-lock findings."""
    codes = _lock_codes("""
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def spawn(self):
                with self._lock:
                    def probe():
                        time.sleep(2.0)
                    threading.Thread(target=probe).start()
    """)
    assert "RTL101" not in codes


def test_lambda_body_runs_lock_free():
    """Same contract as nested defs: a lambda built under a lock runs
    later — ast.walk would descend into its body and mis-attribute its
    calls to the held-lock region (regression: the walker now prunes
    lambda subtrees)."""
    codes = _lock_codes("""
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def m(self):
                with self._lock:
                    self.cb = lambda: time.sleep(5)
    """)
    assert "RTL101" not in codes


# --------------------------------------------------------- suppressions


def test_inline_suppression_silences_exact_code():
    src = textwrap.dedent("""
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)  # raylint: disable=RTL101
    """)
    path = "ray_tpu/_private/_zz_fixture.py"
    mod = acore.Module(path, src)
    findings = lock_discipline.analyze_module_source(src, path)
    assert any(f.code == "RTL101" for f in findings)
    assert not [f for f in findings if not mod.suppressed(f)]
    # a different code on the same line stays live
    other = acore.Finding("RTL104", path, findings[0].line, "C.bad", "x")
    assert not mod.suppressed(other)


def test_baseline_partition_and_staleness():
    f = acore.Finding("RTL101", "ray_tpu/x.py", 7, "C.m", "boom")
    baseline = {f.key: "by design", "RTL102 ray_tpu/gone.py D.n": "old"}
    new, known, stale = acore.partition([f], baseline)
    assert new == [] and known == [f]
    assert stale == ["RTL102 ray_tpu/gone.py D.n"]
    # an unbaselined finding is NEW
    g = acore.Finding("RTL101", "ray_tpu/x.py", 7, "C.other", "boom")
    new, _, _ = acore.partition([g], baseline)
    assert new == [g]


def test_baseline_key_is_line_number_stable():
    a = acore.Finding("RTL101", "ray_tpu/x.py", 7, "C.m", "boom")
    b = acore.Finding("RTL101", "ray_tpu/x.py", 99, "C.m", "boom")
    assert a.key == b.key


def test_readme_knob_tables_match_generated():
    """README's knob tables are GENERATED (`ray-tpu lint --knob-table`)
    — both must appear verbatim, so defaults/docs can't drift from the
    catalog (RTK202 only checks name presence)."""
    import pathlib

    from ray_tpu._private.knobs import readme_knob_table

    root = pathlib.Path(__file__).resolve().parent.parent
    readme = (root / "README.md").read_text()
    for internal in (False, True):
        table = readme_knob_table(internal=internal)
        assert table in readme, (
            f"README's {'internal' if internal else 'user'} knob table "
            f"is out of date — regenerate with `ray-tpu lint "
            f"--knob-table` and paste both tables into the Static "
            f"analysis section")


# ------------------------------------------------------------ knob units


def test_undeclared_knob_fixture():
    findings = knobs_pass.analyze_module_source(textwrap.dedent("""
        import os

        FLAG = os.environ.get("RAY_TPU_TOTALLY_BOGUS_KNOB", "0")
        OTHER = os.environ["RAY_TPU_ANOTHER_BOGUS_ONE"]
    """), "ray_tpu/_zz_fixture.py")
    assert _codes(findings) == {"RTK201"}
    assert {f.context for f in findings} == {
        "RAY_TPU_TOTALLY_BOGUS_KNOB", "RAY_TPU_ANOTHER_BOGUS_ONE"}


def test_declared_and_config_derived_knobs_are_clean():
    findings = knobs_pass.analyze_module_source(textwrap.dedent("""
        import os

        A = os.environ.get("RAY_TPU_INTERNAL_TELEMETRY", "1")
        B = os.getenv("RAY_TPU_COLLECTIVE_OP_TIMEOUT_S")
    """), "ray_tpu/_zz_fixture.py")
    assert findings == []


# ------------------------------------------------------ wire-format units


def _drop_line(text: str, needle: str) -> str:
    kept = [ln for ln in text.splitlines() if needle not in ln]
    assert len(kept) < len(text.splitlines()), f"needle {needle!r} unused"
    return "\n".join(kept) + "\n"


def test_wire_format_clean_on_real_tree():
    ctx = acore.AnalysisContext()
    assert list(wire_format.wire_format_pass(ctx)) == []


def test_deleting_python_protocol_version_fails_wire_pass():
    ctx0 = acore.AnalysisContext()
    real = ctx0.read_text(wire_format.PROTOCOL_PY)
    ctx = acore.AnalysisContext(overrides={
        wire_format.PROTOCOL_PY: _drop_line(real, "PROTOCOL_VERSION = ")})
    codes = _codes(wire_format.wire_format_pass(ctx))
    assert "RTW301" in codes


def test_deleting_cc_protocol_version_fails_wire_pass():
    ctx0 = acore.AnalysisContext()
    real = ctx0.read_text(wire_format.RPC_CC)
    ctx = acore.AnalysisContext(overrides={
        wire_format.RPC_CC: _drop_line(
            real, "constexpr int kProtocolVersion")})
    codes = _codes(wire_format.wire_format_pass(ctx))
    assert "RTW301" in codes


def test_version_desync_fails_wire_pass():
    ctx0 = acore.AnalysisContext()
    real = ctx0.read_text(wire_format.PROTOCOL_PY)
    cur = wire_format.parse_layout(ctx0)["py"]["PROTOCOL_VERSION"]
    tampered = real.replace(f"PROTOCOL_VERSION = {cur}",
                            f"PROTOCOL_VERSION = {cur + 1}")
    assert tampered != real
    ctx = acore.AnalysisContext(
        overrides={wire_format.PROTOCOL_PY: tampered})
    codes = _codes(wire_format.wire_format_pass(ctx))
    assert "RTW302" in codes


def test_oid_layout_tamper_fails_wire_pass():
    """PR 5 regression class: widening the epoch tag past the store's
    16-byte id silently disabled the whole shm fast path — now it's a
    lint failure instead."""
    ctx0 = acore.AnalysisContext()
    real = ctx0.read_text(wire_format.WORKER_PY)
    tampered = real.replace('.to_bytes(4, "big")', '.to_bytes(8, "big")')
    assert tampered != real
    ctx = acore.AnalysisContext(
        overrides={wire_format.WORKER_PY: tampered})
    codes = _codes(wire_format.wire_format_pass(ctx))
    assert "RTW304" in codes


# --------------------------------------------------- native sanitizer gate


@pytest.mark.slow
def test_sanitize_smoke_gate():
    """The native race gate, actually exercised: shells out to
    scripts/sanitize.sh --smoke (tsan-only, small iteration count)
    whenever a C++ compiler is present."""
    import pathlib
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no g++ in this container")
    root = pathlib.Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        ["bash", str(root / "scripts" / "sanitize.sh"), "--smoke", "30"],
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (
        f"sanitize --smoke failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout[-4000:]}\nstderr:\n{proc.stderr[-4000:]}")
    assert "SANITIZE PASS (smoke)" in proc.stdout
