"""IMPALA async-learner tests (reference tier: rllib/algorithms/impala
tuned_examples smoke + multi_gpu_learner_thread decoupling).

Convergence bar mirrors tests/test_rllib.py's PPO bar; the decoupling
test slows the learner artificially and asserts sampling continues
while it is busy (the whole point of the IMPALA architecture).
"""
import numpy as np
import pytest


def test_vtrace_matches_monte_carlo_on_policy():
    """With rho=c=1 and on-policy logps, vs_t must equal the discounted
    n-step return bootstrapped at the horizon (V-trace reduces to the
    on-policy Bellman evaluation)."""
    import jax.numpy as jnp

    from ray_tpu.rllib.impala import vtrace_returns

    T, E = 5, 1
    rng = np.random.default_rng(0)
    rewards = jnp.asarray(rng.normal(size=(T, E)).astype(np.float32))
    values = jnp.asarray(rng.normal(size=(T, E)).astype(np.float32))
    bootstrap = jnp.asarray(rng.normal(size=(E,)).astype(np.float32))
    dones = jnp.zeros((T, E), jnp.float32)
    logp = jnp.zeros((T, E), jnp.float32)      # target == behavior
    gamma = 0.9
    vs, _ = vtrace_returns(logp, logp, rewards, dones, values,
                           bootstrap, gamma)
    # reference recursion computed in plain numpy
    expect = np.zeros((T, E), np.float32)
    nxt = np.asarray(bootstrap)
    for t in reversed(range(T)):
        expect[t] = np.asarray(rewards)[t] + gamma * nxt
        nxt = expect[t]
    assert np.allclose(np.asarray(vs), expect, atol=1e-5)


def test_impala_converges_cartpole(ray_start_regular):
    from ray_tpu.rllib import AlgorithmConfig
    from ray_tpu.rllib.impala import IMPALA

    algo = (AlgorithmConfig(IMPALA)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=64)
            .training(lr=3e-3, num_sgd_steps=8, entropy_coeff=0.01)
            .build())
    try:
        best = 0.0
        for _ in range(12):
            result = algo.train()
            best = max(best, result["episode_reward_mean"])
            if best >= 60.0:
                break
        assert best >= 60.0, f"IMPALA failed to learn: best={best}"
    finally:
        algo.stop()


def test_impala_samplers_not_blocked_on_learner(ray_start_regular):
    """Slow the learner to 0.3 s/step; sampling must continue while it is
    busy (queue decoupling — multi_gpu_learner_thread.py pattern)."""
    from ray_tpu.rllib import AlgorithmConfig
    from ray_tpu.rllib.impala import IMPALA

    algo = (AlgorithmConfig(IMPALA)
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=1,
                      rollout_fragment_length=16)
            .training(num_sgd_steps=6, learner_min_step_s=0.3)
            .build())
    try:
        result = algo.train()
        assert result["learner_steps"] >= 6
        # with a 0.3s learner floor and ~ms-scale sampling, most batches
        # must arrive while the learner is mid-step
        assert result["sampled_while_learning"] >= 2, result
        # and the samplers outpace the learner (decoupled, not lockstep)
        assert result["sample_batches_this_iter"] >= \
            result["learner_steps"] - algo.config.learner_queue_size
    finally:
        algo.stop()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v"]))
