"""Train stack tests: gang scheduling, data-parallel training with gradient
allreduce over the collective layer, checkpoint round trips (the reference's
train/tests tier with DummyTrainer-style configs)."""
import numpy as np
import pytest


def test_worker_group_basics(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.train import WorkerGroup

    wg = WorkerGroup(2, {"CPU": 1})
    try:
        # no train fn started; workers respond to shutdown-style calls
        assert len(wg) == 2
    finally:
        wg.shutdown()


def test_data_parallel_training_allreduce(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import JaxTrainer

    def train_loop(config):
        from ray_tpu.air import session
        from ray_tpu.util import collective as col

        rank = session.get_world_rank()
        world = session.get_world_size()
        assert world == 2
        # "gradient": rank-dependent; allreduce averages across the gang
        for step in range(3):
            grad = np.full(4, float(rank + 1 + step))
            summed = col.allreduce(grad, group_name="train_dp")
            session.report({"step": step,
                            "grad_mean": float(summed.mean()) / world})

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
    )
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    # step s: ranks contribute s+1 and s+2 → mean (2s+3)/2
    assert result.metrics_history[0]["grad_mean"] == pytest.approx(1.5)
    assert result.metrics["grad_mean"] == pytest.approx(3.5)


def test_training_with_checkpoint(ray_start_regular, tmp_path):
    ray = ray_start_regular
    from ray_tpu.air import Checkpoint
    from ray_tpu.air.config import RunConfig, ScalingConfig
    from ray_tpu.train import JaxTrainer

    def train_loop(config):
        from ray_tpu.air import Checkpoint, session

        for step in range(2):
            ckpt = Checkpoint.from_dict({"params": np.ones(3) * step,
                                         "step": step})
            session.report({"loss": 1.0 / (step + 1)}, checkpoint=ckpt)

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ckpt_run", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    state = result.checkpoint.to_dict()
    assert state["step"] == 1
    # persisted to storage_path
    import os

    runs = os.listdir(tmp_path / "ckpt_run")
    assert any(r.startswith("checkpoint_") for r in runs)


def test_train_failure_surfaces(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import JaxTrainer

    def bad_loop(config):
        raise RuntimeError("train exploded")

    trainer = JaxTrainer(bad_loop,
                         scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.error is not None
    assert "train exploded" in str(result.error)


def test_dataset_sharding(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import JaxTrainer

    def train_loop(config):
        from ray_tpu.air import session

        shard = session.get_dataset_shard("train")
        session.report({"shard_len": len(shard),
                        "first": shard[0]})

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": list(range(10))},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["shard_len"] == 5


def test_checkpoint_conversions(tmp_path):
    from ray_tpu.air import Checkpoint

    data = {"w": np.arange(5), "meta": {"lr": 0.1}}
    ckpt = Checkpoint.from_dict(data)
    # dict -> bytes -> checkpoint -> dict
    ckpt2 = Checkpoint.from_bytes(ckpt.to_bytes())
    assert (ckpt2.to_dict()["w"] == data["w"]).all()
    # dict -> dir -> checkpoint -> dict
    d = ckpt.to_directory(str(tmp_path / "c1"))
    ckpt3 = Checkpoint.from_directory(d)
    assert ckpt3.to_dict()["meta"]["lr"] == 0.1
    # uri round trip
    uri = ckpt.to_uri(f"file://{tmp_path}/c2")
    ckpt4 = Checkpoint.from_uri(uri)
    assert (ckpt4.to_dict()["w"] == data["w"]).all()


def test_batch_predictor_over_dataset(ray_start_regular):
    """Checkpoint -> JaxPredictor -> BatchPredictor scores a Dataset on an
    actor pool (reference: train/batch_predictor.py)."""
    import jax.numpy as jnp

    from ray_tpu import data
    from ray_tpu.air import Checkpoint
    from ray_tpu.train import BatchPredictor, JaxPredictor

    w = np.array([[2.0], [3.0]], np.float32)          # y = 2a + 3b
    ckpt = Checkpoint.from_dict({"params": {"w": w}})

    def apply_fn(params, x):
        return jnp.asarray(x) @ params["w"]

    ds = data.from_numpy(
        np.array([[1.0, 1.0], [2.0, 0.0], [0.0, 2.0]], np.float32),
        parallelism=3)
    bp = BatchPredictor.from_checkpoint(ckpt, JaxPredictor,
                                        apply_fn=apply_fn)
    out = bp.predict(ds, num_scoring_workers=2)
    got = np.concatenate([np.asarray(b) for b in out.blocks()]).ravel()
    assert np.allclose(sorted(got.tolist()), [4.0, 5.0, 6.0])


def test_torch_trainer_ddp_gloo(ray_start_regular):
    """TorchTrainer: gloo process group across the actor gang, DDP syncs
    gradients (reference: train/torch/config.py:69 + torch_trainer.py)."""
    from ray_tpu.train import TorchTrainer

    def train_loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.air import session
        from ray_tpu.train.torch import prepare_model

        assert dist.is_initialized()
        rank = session.get_world_rank()
        torch.manual_seed(0)          # same init on every worker
        model = prepare_model(torch.nn.Linear(2, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        # rank-dependent data: without DDP allreduce the workers diverge
        g = torch.Generator().manual_seed(42 + rank)
        x = torch.randn(64, 2, generator=g)
        y = (x @ torch.tensor([[2.0], [-3.0]])) + 1.0
        for step in range(30):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
        w = [p.detach().clone() for p in model.parameters()]
        session.report({
            "loss": float(loss),
            "w_sum": float(sum(p.sum() for p in w)),
        })

    result = TorchTrainer(
        train_loop,
        scaling_config=__import__("ray_tpu.air",
                                  fromlist=["ScalingConfig"]).ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1}),
    ).fit()
    assert result.error is None
    assert result.metrics["loss"] < 1.0   # learned the line
