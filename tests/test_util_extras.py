"""ActorPool and distributed Queue tests (reference:
python/ray/tests/test_actor_pool.py, test_queue.py)."""
import pytest


def test_actor_pool_map(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util.actor_pool import ActorPool

    @ray.remote
    class Doubler:
        def double(self, x):
            return 2 * x

    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), list(range(8))))
    assert out == [2 * i for i in range(8)]


def test_actor_pool_unordered(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util.actor_pool import ActorPool

    @ray.remote
    class Sleeper:
        def work(self, t):
            import time

            time.sleep(t)
            return t

    pool = ActorPool([Sleeper.remote() for _ in range(2)])
    out = list(pool.map_unordered(lambda a, v: a.work.remote(v),
                                  [0.4, 0.05]))
    assert sorted(out) == [0.05, 0.4]
    assert out[0] == 0.05, "unordered map must yield fastest first"


def test_queue_basic(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util.queue import Empty, Queue

    q = Queue(maxsize=4)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"
    assert q.get() == "b"
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_across_tasks(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util.queue import Queue

    q = Queue()

    @ray.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    @ray.remote
    def consumer(q, n):
        return [q.get(timeout=30) for _ in range(n)]

    p = producer.remote(q, 5)
    c = consumer.remote(q, 5)
    assert ray.get(p, timeout=60)
    assert sorted(ray.get(c, timeout=60)) == list(range(5))
    q.shutdown()


def test_multiprocessing_pool(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    def square(x):
        return x * x

    def add(a, b):
        return a + b

    with Pool(processes=2) as pool:
        assert pool.map(square, range(10)) == [x * x for x in range(10)]
        assert pool.apply(add, (3, 4)) == 7
        r = pool.apply_async(square, (9,))
        assert r.get(timeout=30) == 81
        assert sorted(pool.imap_unordered(square, range(6))) == \
            [0, 1, 4, 9, 16, 25]
        assert list(pool.imap(square, range(5))) == [0, 1, 4, 9, 16]
        assert pool.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        # stdlib contract: map passes tuple items as ONE argument
        assert pool.map(len, [(1, 2), (3, 4, 5)]) == [2, 3]
        r = pool.map_async(square, range(4))
        assert r.get(timeout=60) == [0, 1, 4, 9] and r.successful()


def test_inspect_serializability():
    import threading

    from ray_tpu.util.check_serialize import inspect_serializability

    ok, failures = inspect_serializability(lambda x: x + 1)
    assert ok and not failures

    lock = threading.Lock()

    def closes_over_lock():
        return lock

    import io
    buf = io.StringIO()
    ok, failures = inspect_serializability(closes_over_lock,
                                           print_file=buf)
    assert not ok
    assert "lock" in {f.split(".")[-1] for f in failures} or failures


def test_joblib_backend(ray_start_regular):
    import joblib

    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray", n_jobs=4):
        out = joblib.Parallel()(joblib.delayed(lambda x: x * x)(i)
                                for i in range(8))
    assert out == [i * i for i in range(8)]


def test_parallel_iterator(ray_start_regular):
    from ray_tpu.util import iter as par_iter

    it = par_iter.from_range(12, num_shards=3)
    assert it.num_shards == 3
    out = sorted(it.for_each(lambda x: x * 2).gather_sync())
    assert out == [x * 2 for x in range(12)]

    evens = par_iter.from_range(10, num_shards=2).filter(
        lambda x: x % 2 == 0)
    assert sorted(evens.gather_sync()) == [0, 2, 4, 6, 8]

    batches = par_iter.from_range(8, num_shards=2).batch(2).gather_sync()
    assert sorted(x for b in batches for x in b) == list(range(8))

    async_out = sorted(
        par_iter.from_range(9, num_shards=3).gather_async(num_async=2))
    assert async_out == list(range(9))

    assert par_iter.from_items([1, 2, 3], num_shards=2).count() == 3
    assert len(par_iter.from_range(20, num_shards=4).take(5)) == 5

    u = par_iter.from_range(3, num_shards=1).union(
        par_iter.from_items([10, 11], num_shards=1))
    assert sorted(u.gather_sync()) == [0, 1, 2, 10, 11]


def test_list_named_actors(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import list_named_actors

    @ray_tpu.remote
    class N:
        def ping(self):
            return 1

    a = N.options(name="named_one").remote()
    ray_tpu.get(a.ping.remote())
    assert "named_one" in list_named_actors()
    rows = list_named_actors(all_namespaces=True)
    assert any(r["name"] == "named_one" for r in rows)
