"""Serve-plane benchmark: sustained QPS + latency under seeded chaos.

Round 7 (PR 6). Three phases, one JSON artifact:

1. **shape_proof** — the shape-aware batching acceptance claim, run
   hermetically (no cluster): a fixed mixed batch-size traffic stream is
   replayed through the bucketing batcher and through the legacy
   (``RAY_TPU_SERVE_SHAPE_BUCKETS=0``) batcher, recording the
   ``ray_tpu_pjit_cache_total`` miss curve after every batch. Bucketed
   must go flat after warmup (one compile per bucket); legacy keeps
   compiling — one miss per distinct raw batch size.

2. **steady** — closed-loop load (``--threads`` callers, ``--duration``
   seconds) against an unchaosed deployment: sustained QPS, p50/p99
   latency, batching stats (mean executed batch size, pad waste).

3. **chaos** — the same load against a deployment whose replicas are
   killed mid-load by the seeded fault DSL
   (``kill_actor:serve-bench-Model.handle_request:#N`` — every replica
   process os._exits at its Nth request dispatch, so kills keep landing
   as the controller back-fills). Acceptance: ZERO lost accepted
   requests (every non-shed request returns the correct result) and
   sub-second p99 recovery for the kill-affected tail.

Usage::

    JAX_PLATFORMS=cpu python benchmarks/bench_serve.py \
        --duration 8 --threads 12 --replicas 3 --json-out BENCH_r07.json
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


# --------------------------------------------------------------- phase 1

TRAFFIC = [3, 1, 5, 2, 7, 4, 8, 6, 3, 5, 7, 1, 6, 2, 8, 4,
           5, 3, 6, 1, 7, 2, 4, 8]


def shape_proof() -> dict:
    import numpy as np

    from ray_tpu.serve.batching import _Batcher
    from ray_tpu.util.metrics import registry_snapshot

    def misses(name):
        fam = next((m for m in registry_snapshot()
                    if m["name"] == "ray_tpu_pjit_cache_total"), None)
        if fam is None:
            return 0.0
        return sum(v["value"] for v in fam["values"]
                   if v["tags"].get("fn") == f"serve_batch::{name}"
                   and v["tags"].get("result") == "miss")

    def replay(name):
        b = _Batcher(lambda xs: [x.sum() for x in xs], 8, 0.001, name=name)
        curve = []
        for n in TRAFFIC:
            items, _ = b._pad_to_bucket([np.zeros((16, 8))] * n)
            b._fn(items)
            curve.append(misses(name))
        return curve

    bucketed = replay("bench_bucketed")
    os.environ["RAY_TPU_SERVE_SHAPE_BUCKETS"] = "0"
    try:
        legacy = replay("bench_legacy")
    finally:
        os.environ.pop("RAY_TPU_SERVE_SHAPE_BUCKETS", None)
    warm = 4                       # traffic touches buckets {1,2,4,8}
    return {
        "traffic_batch_sizes": TRAFFIC,
        "bucketed_miss_curve": bucketed,
        "legacy_miss_curve": legacy,
        "bucketed_misses_total": bucketed[-1],
        "legacy_misses_total": legacy[-1],
        "bucketed_flat_after_warmup": bucketed[warm - 1] == bucketed[-1],
        "claim": "bucketed compiles once per bucket then goes flat; "
                 "legacy recompiles for every distinct raw batch size",
    }


# ----------------------------------------------------------- load driver

def drive_load(handle, duration_s: float, threads: int, dim: int):
    """Closed-loop load: each thread issues one request and blocks on
    its result. Returns per-request (latency, ok) plus shed count."""
    import numpy as np

    results = []           # (latency_s, ok)
    sheds = [0]
    lost = [0]
    lock = threading.Lock()
    stop = time.monotonic() + duration_s
    rng = np.random.default_rng(7)
    payloads = [rng.standard_normal(dim).astype(np.float32)
                for _ in range(32)]

    def worker(widx):
        from ray_tpu.exceptions import ServeOverloadedError

        i = 0
        while time.monotonic() < stop:
            x = payloads[(widx + i) % len(payloads)]
            t0 = time.monotonic()
            try:
                resp = handle.remote(x)
                out = resp.result(timeout_s=30)
                ok = bool(np.isfinite(out))
                with lock:
                    results.append((time.monotonic() - t0, ok,
                                    resp.num_failovers))
                    if not ok:
                        lost[0] += 1
            except ServeOverloadedError:
                with lock:
                    sheds[0] += 1
                time.sleep(0.01)   # honor the backpressure contract
            except Exception:
                with lock:
                    results.append((time.monotonic() - t0, False, 0))
                    lost[0] += 1
            i += 1

    ts = [threading.Thread(target=worker, args=(w,)) for w in range(threads)]
    t_start = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.monotonic() - t_start
    return results, sheds[0], lost[0], wall


def summarize_load(results, sheds, lost, wall) -> dict:
    lats = sorted(l for l, ok, _ in results if ok)
    # recovery = latency of exactly the requests that FAILED OVER (their
    # first replica died or drained mid-request) — attributed per
    # request via DeploymentResponse.num_failovers, not guessed from a
    # latency threshold that cgroup stragglers also cross
    failed_over = sorted(l for l, ok, nf in results if ok and nf > 0)
    return {
        "requests_ok": len(lats),
        "requests_lost": lost,
        "requests_shed": sheds,
        "wall_s": round(wall, 3),
        "qps": round(len(lats) / wall, 1) if wall else 0.0,
        "p50_ms": round(_percentile(lats, 0.50) * 1e3, 2),
        "p99_ms": round(_percentile(lats, 0.99) * 1e3, 2),
        "worst_ms": round((lats[-1] if lats else 0.0) * 1e3, 2),
        "mean_ms": round(statistics.fmean(lats) * 1e3, 2) if lats else 0.0,
        "failed_over_requests": len(failed_over),
        "recovery_p99_s": round(_percentile(failed_over, 0.99), 3),
        "recovery_worst_s": round(failed_over[-1] if failed_over else 0.0,
                                  3),
    }


# --------------------------------------------------------------- serving

def build_model(serve, app_name: str, replicas: int, dim: int):
    import numpy as np

    @serve.deployment(num_replicas=replicas, max_ongoing_requests=8,
                      max_queued_requests=64)
    class Model:
        def __init__(self, dim):
            rng = np.random.default_rng(0)
            self._w = rng.standard_normal((dim, dim)).astype(np.float32)

        @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.002)
        def predict(self, xs):
            batch = np.stack(xs)
            out = batch @ self._w
            return [float(abs(row).sum()) for row in out]

        def __call__(self, x):
            return self.predict(x)

    return serve.run(Model.bind(dim), name=app_name, route_prefix=None)


def failover_count(deployment: str) -> float:
    from ray_tpu.util.metrics import registry_snapshot

    fam = next((m for m in registry_snapshot()
                if m["name"] == "ray_tpu_serve_failovers_total"), None)
    return sum(v["value"] for v in (fam["values"] if fam else [])
               if v["tags"].get("deployment") == deployment)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=8.0)
    ap.add_argument("--threads", type=int, default=12)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--kill-every", type=int, default=60,
                    help="each replica process dies at its Nth request")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args()

    out = {
        "round": 7,
        "harness": "benchmarks/bench_serve.py",
        "config": {"duration_s": args.duration, "threads": args.threads,
                   "replicas": args.replicas, "dim": args.dim,
                   "kill_every": args.kill_every, "seed": args.seed},
        "methodology": (
            "closed-loop load from one driver (threads blocking on "
            "result()); chaos phase arms the seeded kill_actor DSL so "
            "every replica process of the chaos app os._exits at its "
            "Nth handle_request dispatch (replacements inherit the "
            "schedule and the slot tag, so kills continue all run); "
            "recovery_p99_s = p99 end-to-end latency of exactly the "
            "requests that failed over (DeploymentResponse."
            "num_failovers > 0), i.e. accepted requests whose replica "
            "died mid-request — the failover-recovery claim"),
    }

    print("== phase 1: shape-aware batching proof (hermetic)")
    out["shape_proof"] = shape_proof()
    print(json.dumps(out["shape_proof"], indent=2))

    # chaos env must precede init so replica processes inherit it; the
    # schedule is scoped to the chaos app's process tag, so the steady
    # phase (different app name → different tag) runs unchaosed
    # Target ONE slot's replica lineage: a deployment-wide rule fires at
    # the same per-process call count in every (identical) replica, so
    # all replicas die in synchronized waves — a fleet-annihilation
    # benchmark, not failover. Slot 0 (and each of its replacements)
    # dying every N requests measures the real thing: minority capacity
    # loss under load, survivors absorbing re-dispatched traffic.
    os.environ["RAY_TPU_FAULT_SEED"] = str(args.seed)
    os.environ["RAY_TPU_FAULT_SCHEDULE"] = (
        f"kill_actor:serve-bench-Model-slot0.handle_request:"
        f"#{args.kill_every}")
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, args.replicas + 2),
                 object_store_memory=128 * 1024 * 1024)
    import ray_tpu.serve as serve

    try:
        import numpy as np

        def warmup(handle, n=48):
            """Warm every replica + batch bucket BEFORE the measured
            window: deploy-time and first-dispatch costs are startup,
            not serving latency."""
            for _ in range(n):
                handle.remote(np.zeros(args.dim, dtype="float32")).result()

        print("== phase 2: steady-state load (no chaos)")
        h = build_model(serve, "steady", args.replicas, args.dim)
        warmup(h)
        steady = summarize_load(*drive_load(h, args.duration,
                                            args.threads, args.dim))
        out["steady"] = steady
        print(json.dumps(steady, indent=2))
        # free the steady replicas: the chaos phase must not compete
        # with idle capacity on a small CPU cgroup
        serve.delete("steady")

        print("== phase 3: chaos load (seeded replica kills mid-load)")
        h2 = build_model(serve, "bench", args.replicas, args.dim)
        # NOTE: warmup calls count toward each replica's kill schedule
        # position — keep it below --kill-every so the measured window
        # starts with all replicas alive
        warmup(h2, n=min(48, max(1, (args.kill_every - 8) // 2)))
        base_failovers = failover_count("bench#Model")
        chaos = summarize_load(*drive_load(h2, args.duration,
                                           args.threads, args.dim))
        chaos["failovers"] = failover_count("bench#Model") - base_failovers
        out["chaos"] = chaos
        print(json.dumps(chaos, indent=2))

        from ray_tpu.experimental.state.api import summarize_serve

        rollup = summarize_serve()
        out["batching"] = rollup.get("batching", {})
        replica_deaths = sum(
            1 for e in rollup.get("events", [])
            if e.get("kind") == "REPLICA_DIED"
            and str(e.get("deployment", "")).startswith("bench#"))
        chaos["replica_deaths_observed"] = replica_deaths

        out["acceptance"] = {
            "zero_lost_accepted_requests":
                steady["requests_lost"] == 0 and
                chaos["requests_lost"] == 0,
            "kills_landed": chaos["failovers"] >= 1,
            "recovery_p99_s": chaos["recovery_p99_s"],
            "recovery_p99_under_1s": chaos["recovery_p99_s"] < 1.0,
            "bucketed_flat_after_warmup":
                out["shape_proof"]["bucketed_flat_after_warmup"],
            "legacy_kept_recompiling":
                out["shape_proof"]["legacy_misses_total"]
                > out["shape_proof"]["bucketed_misses_total"],
        }
        print("== acceptance")
        print(json.dumps(out["acceptance"], indent=2))
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_FAULT_SEED", None)
        os.environ.pop("RAY_TPU_FAULT_SCHEDULE", None)

    import datetime

    out["date"] = datetime.date.today().isoformat()
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"wrote {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
