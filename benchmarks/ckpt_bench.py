"""Sharded-checkpoint benchmark — PERF.md round 20 artifact.

Phases, one JSON artifact (BENCH_r20.json), all single-process over the
groupless save path (the commit discipline — payload build, atomic
shard write, scan-ack, manifest — is identical to the gang path minus
one small allgather, so the disk-side numbers transfer):

1. **write/restore throughput** — sync `save_sharded` of an N-MB param
   tree followed by `restore_sharded`, repeated; p50 wall + MB/s for
   each (fsync on: these are the durable numbers).
2. **async on/off step delta** — the acceptance measurement: a
   simulated train loop (fixed ~tens-of-ms numpy compute per step)
   checkpointing EVERY step, `asynchronous=False` (write inline on the
   step) vs `asynchronous=True` (write on the background thread,
   harvested at the NEXT step's boundary — the overlap window a real
   loop has). The headline is p50 step wall in each mode: the delta is
   the checkpoint stall the async path hides behind compute.
3. **reshard cost** — restore p50 from a world-2 save at world 2
   (same-world) vs world 4 (elastic 2->4), and from a world-4 save at
   world 2 (4->2): the price of the reslice index math + touching more
   shard files, over identical bytes.

Usage:
  python benchmarks/ckpt_bench.py --json-out BENCH_r20.json
  python benchmarks/ckpt_bench.py --total-mb 32 --bucket-mb 4 \
      --steps 12 --repeats 5
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _make_params(total_bytes: int, n_leaves: int):
    per = max(1, int(total_bytes) // 4 // n_leaves)
    rng = np.random.RandomState(7)
    return {f"w{i:02d}": rng.standard_normal(per).astype(np.float32)
            for i in range(n_leaves)}


def _p50(xs):
    return statistics.median(xs)


def bench_write_restore(root, params, bucket_bytes, repeats, total_bytes,
                        warmup=3):
    from ray_tpu.train import sharded_checkpoint as sc

    # first few saves pay cold-page/fs costs 4-5x steady state; burn
    # them so the row reports steady-state throughput
    for i in range(warmup):
        sc.save_sharded(params, root=root, step=i, bucket_bytes=bucket_bytes,
                        keep=2, asynchronous=False).result()
    writes, restores = [], []
    for i in range(warmup, warmup + repeats):
        t0 = time.perf_counter()
        res = sc.save_sharded(params, root=root, step=i,
                              bucket_bytes=bucket_bytes, keep=2,
                              asynchronous=False).result()
        writes.append(time.perf_counter() - t0)
        assert res["committed"], res
        t0 = time.perf_counter()
        out = sc.restore_sharded(params, root=root,
                                 bucket_bytes=bucket_bytes)
        restores.append(time.perf_counter() - t0)
        assert out is not None
    mb = total_bytes / 1e6
    return {"phase": "write_restore", "total_bytes": total_bytes,
            "bucket_bytes": bucket_bytes, "repeats": repeats,
            "p50_write_s": round(_p50(writes), 6),
            "p50_restore_s": round(_p50(restores), 6),
            "write_MBps": round(mb / _p50(writes), 1),
            "restore_MBps": round(mb / _p50(restores), 1)}


def _step_work(x, w, iters):
    for _ in range(iters):
        x = np.tanh(x @ w)
    return x


def bench_async_step(root, params, bucket_bytes, asynchronous, steps,
                     work_iters, total_bytes):
    """p50 step wall with a per-step checkpoint, async write overlapped
    under the NEXT step's compute vs written inline."""
    from ray_tpu.train import sharded_checkpoint as sc

    rng = np.random.RandomState(3)
    x = rng.standard_normal((256, 512)).astype(np.float32)
    w = rng.standard_normal((512, 512)).astype(np.float32)
    warmup = 2
    for step in range(warmup):
        sc.save_sharded(params, root=root, step=step,
                        bucket_bytes=bucket_bytes, keep=2,
                        asynchronous=False).result()
    walls, pending = [], None
    for step in range(warmup, warmup + steps):
        t0 = time.perf_counter()
        x = _step_work(x, w, work_iters)        # the overlap window
        params = {k: v + 1.0 for k, v in params.items()}   # "update"
        if pending is not None:
            assert pending.result(timeout=300)["committed"]
            pending = None
        p = sc.save_sharded(params, root=root, step=step,
                            bucket_bytes=bucket_bytes, keep=2,
                            asynchronous=asynchronous)
        if asynchronous:
            pending = p                          # harvest next step
        else:
            assert p.result()["committed"]
        walls.append(time.perf_counter() - t0)
    if pending is not None:
        pending.result(timeout=300)
    return {"phase": "async_step", "asynchronous": bool(asynchronous),
            "total_bytes": total_bytes, "bucket_bytes": bucket_bytes,
            "steps": steps, "work_iters": work_iters,
            "p50_step_s": round(_p50(walls), 6),
            "best_step_s": round(min(walls), 6)}


def bench_reshard(root_base, params, bucket_bytes, repeats, total_bytes):
    from ray_tpu.train import sharded_checkpoint as sc

    rows = []
    for save_world, restore_world in ((2, 2), (2, 4), (4, 2)):
        root = os.path.join(root_base, f"w{save_world}to{restore_world}")
        pendings = [sc.save_sharded(params, root=root, step=1,
                                    world=save_world, rank=r,
                                    bucket_bytes=bucket_bytes,
                                    asynchronous=False)
                    for r in range(save_world)]
        for r in range(save_world - 1, -1, -1):
            assert pendings[r].result()["committed"]
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = sc.restore_sharded(params, root=root,
                                     world=restore_world, rank=0,
                                     bucket_bytes=bucket_bytes)
            times.append(time.perf_counter() - t0)
            assert out is not None
            assert out[1]["resharded"] == (save_world != restore_world)
        rows.append({"phase": "reshard", "world_saved": save_world,
                     "world_restore": restore_world,
                     "total_bytes": total_bytes,
                     "bucket_bytes": bucket_bytes,
                     "resharded": save_world != restore_world,
                     "p50_restore_s": round(_p50(times), 6)})
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--total-mb", type=float, default=32.0)
    ap.add_argument("--leaves", type=int, default=16)
    ap.add_argument("--bucket-mb", type=float, default=4.0)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--work-iters", type=int, default=48,
                    help="per-step compute; default sized so the "
                         "compute window exceeds one steady-state "
                         "shard write")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--root", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args(argv)

    total = int(args.total_mb * 1e6)
    bb = int(args.bucket_mb * 1e6)
    params = _make_params(total, args.leaves)
    scratch = args.root or tempfile.mkdtemp(prefix="ckpt_bench_")
    rows = []
    try:
        rows.append(bench_write_restore(
            os.path.join(scratch, "wr"), params, bb, args.repeats, total))
        print(json.dumps(rows[-1]))
        for asynchronous in (False, True):
            rows.append(bench_async_step(
                os.path.join(scratch, f"as{int(asynchronous)}"), params,
                bb, asynchronous, args.steps, args.work_iters, total))
            print(json.dumps(rows[-1]))
        for row in bench_reshard(os.path.join(scratch, "rs"), params,
                                 bb, args.repeats, total):
            rows.append(row)
            print(json.dumps(row))
    finally:
        if args.root is None:
            shutil.rmtree(scratch, ignore_errors=True)

    out = {"harness": "benchmarks/ckpt_bench.py",
           "argv": list(argv if argv is not None else sys.argv[1:]),
           "rows": rows}
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"wrote {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
