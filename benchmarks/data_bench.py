"""Streaming data plane benchmark — ingest throughput + data-wait fraction.

Measures the ROADMAP "Streaming data plane" acceptance: a multi-epoch
train run over a dataset larger than the prefetch budget where per-step
data wait is <5% of step time, measured by the
`ray_tpu_data_wait_seconds` telemetry the plane stamps.

Two phases, both comparing streaming (default) vs the legacy
materialize-then-iterate path (`RAY_TPU_DATA_STREAMING=0`), with and
without `device_put`:

  ingest   driver-side iteration with a simulated per-batch train step
           (`--step-ms` busy wait): reports rows/s, MB/s, and the
           data-wait fraction wait/(wait+step) per config, plus a
           bit-equality check between the two paths.

  train    a real 2-worker Train gang: each rank iterates its shard via
           `session.get_dataset_shard` (consumer-tagged
           `train/<ds>/rank<k>`), runs a jnp step per batch over
           `--epochs` epochs, and the harness folds the gang's
           `ray_tpu_data_wait_seconds` against measured step time into
           the acceptance ratio.

Usage:
  python benchmarks/data_bench.py --json-out BENCH_r09.json
  python benchmarks/data_bench.py --phase ingest --rows 200000
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def emit(result: dict):
    print(json.dumps(result), flush=True)


def _busy_wait(seconds: float):
    """Spin (not sleep): a sleeping consumer yields its core to the
    prefetch threads, which would flatter the legacy path's overlap."""
    end = time.perf_counter() + seconds
    while time.perf_counter() < end:
        pass


def _make_dataset(rows: int, dim: int, blocks: int):
    from ray_tpu import data

    arr = np.arange(rows * dim, dtype=np.float32).reshape(rows, dim)
    return data.from_numpy(arr, parallelism=blocks), arr.nbytes


def bench_ingest(args) -> list[dict]:
    import ray_tpu

    ds, nbytes = _make_dataset(args.rows, args.dim, args.blocks)
    step_s = args.step_ms / 1000.0
    out = []
    configs = [(s, d) for s in ("streaming", "legacy")
               for d in ((False, True) if args.device_put else (False,))]
    if args.device_put:
        import jax

        jax.device_put(np.zeros(8, dtype=np.float32)).block_until_ready()
    digests: dict = {}
    for mode, device_put in configs:
        os.environ["RAY_TPU_DATA_STREAMING"] = (
            "1" if mode == "streaming" else "0")
        for repeat in range(args.repeats):
            wait_s = 0.0
            n_rows = 0
            n_batches = 0
            digest = 0
            t_start = time.perf_counter()
            it = ds.iter_batches(batch_size=args.batch_size,
                                 device_put=device_put)
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                wait_s += time.perf_counter() - t0
                if device_put:
                    batch.block_until_ready()
                    n_rows += batch.shape[0]
                else:
                    n_rows += len(batch)
                n_batches += 1
                if repeat == 0 and not device_put:
                    digest ^= hash(np.asarray(batch).tobytes())
                if step_s:
                    _busy_wait(step_s)
            total_s = time.perf_counter() - t_start
            step_total = n_batches * step_s
            row = {
                "phase": "ingest", "mode": mode,
                "device_put": device_put, "repeat": repeat,
                "rows": n_rows, "batches": n_batches,
                "total_s": round(total_s, 4),
                "wait_s": round(wait_s, 4),
                "rows_per_s": round(n_rows / total_s, 1),
                "mb_per_s": round(nbytes / total_s / 1e6, 1),
                "wait_frac": round(
                    wait_s / (wait_s + step_total), 4)
                if step_total else None,
            }
            if repeat == 0 and not device_put:
                digests[mode] = digest
            emit(row)
            out.append(row)
    os.environ["RAY_TPU_DATA_STREAMING"] = "1"
    if len(digests) == 2:
        match = digests["streaming"] == digests["legacy"]
        row = {"phase": "ingest", "check": "bit_equality",
               "streaming_equals_legacy": bool(match)}
        emit(row)
        out.append(row)
        assert match, "streaming output diverged from legacy!"
    _ = ray_tpu
    return out


def bench_bounded(args) -> list[dict]:
    """Peak object-store occupancy of a transformed dataset: the legacy
    path materializes every map-stage output block up front, streaming
    submits tasks on demand and frees consumed blocks — store growth is
    ~the prefetch budget instead of the whole transformed dataset."""
    from ray_tpu._private.worker_runtime import current_worker

    ds, nbytes = _make_dataset(args.rows, args.dim, args.blocks)
    mapped = ds.map_batches(lambda a: a * 2)
    store = current_worker().store
    out = []
    for mode in ("streaming", "legacy"):
        os.environ["RAY_TPU_DATA_STREAMING"] = (
            "1" if mode == "streaming" else "0")
        time.sleep(0.3)   # let the ref reaper settle between modes
        base = store.stats()["bytes_used"]
        peak = base
        n_rows = 0
        for batch in mapped.iter_batches(batch_size=args.batch_size):
            n_rows += len(batch)
            peak = max(peak, store.stats()["bytes_used"])
        row = {"phase": "bounded", "mode": mode, "rows": n_rows,
               "dataset_mb": round(nbytes / 1e6, 1),
               "peak_extra_mb": round((peak - base) / 1e6, 1)}
        emit(row)
        out.append(row)
    os.environ["RAY_TPU_DATA_STREAMING"] = "1"
    return out


def _train_loop(config):
    import jax.numpy as jnp

    from ray_tpu.air import session
    from ray_tpu.util import metrics as um

    shard = session.get_dataset_shard("train")
    w = None
    steps = 0
    step_time = 0.0
    jnp.zeros(8).block_until_ready()   # warm the jax dispatch path
    for _epoch in range(config["epochs"]):
        for batch in shard.iter_batches(batch_size=config["batch_size"],
                                        device_put=True):
            t0 = time.perf_counter()
            x = jnp.asarray(batch)
            if w is None:
                w = jnp.ones((x.shape[1],), dtype=x.dtype)
            w = w + 1e-6 * (x * x).sum(axis=0)
            w.block_until_ready()
            dt = time.perf_counter() - t0
            if config["step_ms"]:
                _busy_wait(config["step_ms"] / 1000.0)
                dt += config["step_ms"] / 1000.0
            step_time += dt
            steps += 1
    # This rank's data wait, read from the telemetry plane's histogram
    # (the shard's consumer tag is stamped by the Train feed).
    me = getattr(shard, "_consumer", "default")
    wait_s, wait_batches = 0.0, 0
    for snap in um.registry_snapshot():
        if snap.get("name") != "ray_tpu_data_wait_seconds":
            continue
        for v in snap.get("values", []):
            if v["tags"].get("consumer") == me:
                wait_s = v["value"]
        for c in snap.get("counts", []):
            if c["tags"].get("consumer") == me:
                wait_batches = sum(c["counts"])
    session.report({"steps": steps, "step_time_s": step_time,
                    "data_wait_s": wait_s,
                    "wait_batches": wait_batches, "consumer": me,
                    "checksum": float(w.sum())})


def bench_train(args) -> list[dict]:
    import threading

    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.experimental.state.api import summarize_data
    from ray_tpu.train import JaxTrainer

    ds, nbytes = _make_dataset(args.rows, args.dim, args.blocks)
    budget = int(os.environ.get("RAY_TPU_DATA_PREFETCH_BLOCKS", "4"))
    trainer = JaxTrainer(
        _train_loop,
        train_loop_config={"epochs": args.epochs,
                           "batch_size": args.batch_size,
                           "step_ms": args.step_ms},
        scaling_config=ScalingConfig(num_workers=args.workers),
        datasets={"train": ds})
    # Poll the cross-process rollup while the gang is alive (worker
    # metric rings die with their processes at gang teardown).
    polled: dict[str, dict] = {}
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            try:
                for r in summarize_data()["consumers"]:
                    if r["consumer"].startswith("train/"):
                        prev = polled.get(r["consumer"])
                        if prev is None or r["batches"] >= prev["batches"]:
                            polled[r["consumer"]] = r
            except Exception:
                pass
            stop.wait(0.3)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    t0 = time.perf_counter()
    result = trainer.fit()
    wall_s = time.perf_counter() - t0
    stop.set()
    poller.join(timeout=5)
    if result.error is not None:
        raise result.error
    # Rank 0's own numbers (read from its wait histogram in-process
    # before teardown) give the exact per-rank acceptance ratio; wait
    # and step are disjoint phases of the loop, so the fraction is
    # wait / step — the strict reading of "data wait <5% of step time".
    steps = result.metrics["steps"]
    step_time_s = result.metrics["step_time_s"]
    wait_s = result.metrics["data_wait_s"]
    wait_frac = (wait_s / step_time_s) if step_time_s else None
    row = {
        "phase": "train", "workers": args.workers,
        "epochs": args.epochs,
        "blocks_per_shard": args.blocks // args.workers,
        "prefetch_budget": budget,
        "dataset_mb": round(nbytes / 1e6, 1),
        "rank0_steps": steps,
        "rank0_batches_waited": result.metrics["wait_batches"],
        "wall_s": round(wall_s, 3),
        "rank0_step_time_s": round(step_time_s, 4),
        "rank0_data_wait_s": round(wait_s, 4),
        "data_wait_frac_of_step": round(wait_frac, 4)
        if wait_frac is not None else None,
        "gang_consumers_polled": {
            k: {"batches": v["batches"],
                "wait_total_s": round(v["wait_total_s"], 4),
                "blocks_local": v["blocks_local"],
                "blocks_remote": v["blocks_remote"]}
            for k, v in sorted(polled.items())},
        "accept_lt_0.05": bool(wait_frac is not None
                               and wait_frac < 0.05),
    }
    emit(row)
    return [row]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--phase",
                   choices=("ingest", "bounded", "train", "all"),
                   default="all")
    p.add_argument("--rows", type=int, default=120_000)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--blocks", type=int, default=24)
    p.add_argument("--batch-size", type=int, default=1024)
    p.add_argument("--step-ms", type=float, default=5.0)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--device-put", action="store_true", default=True)
    p.add_argument("--no-device-put", dest="device_put",
                   action="store_false")
    p.add_argument("--json-out", default=None)
    args = p.parse_args()

    import ray_tpu

    ray_tpu.init(num_cpus=max(4, args.workers + 2),
                 object_store_memory=256 * 1024 * 1024)
    rows = []
    try:
        if args.phase in ("ingest", "all"):
            rows += bench_ingest(args)
        if args.phase in ("bounded", "all"):
            rows += bench_bounded(args)
        if args.phase in ("train", "all"):
            rows += bench_train(args)
    finally:
        ray_tpu.shutdown()
    if args.json_out:
        doc = {
            "bench": "data_streaming", "round": 9,
            "argv": sys.argv[1:],
            "config": {k: getattr(args, k) for k in
                       ("rows", "dim", "blocks", "batch_size", "step_ms",
                        "epochs", "workers", "repeats")},
            "results": rows,
        }
        with open(args.json_out, "w") as f:
            json.dump(doc, f, indent=2)
        print(f"wrote {args.json_out}", file=sys.stderr)


if __name__ == "__main__":
    main()
