"""Bucketed-DDP gradient-sync benchmark — PERF.md round 15 artifact,
extended with the ZeRO sharded mode for round 19.

Phases, one JSON artifact (BENCH_r15.json / BENCH_r19.json):

1. **handle overhead** (`collective_bench.run_async_sweep`): sync
   allreduce baseline vs `allreduce_async` at submission windows 1 and
   4 — window 1 isolates the per-op cost of the handle plane (submit +
   issue-thread handoff + handle wakeup), deeper windows measure the
   pipelined submission path bucketed DDP rides.
2. **train grad-sync step** — the acceptance measurement: a 2-worker
   gang syncing a comm-bound grad pytree (default 64 MB, far past the
   8 MB BENCH_r06/r08 regime) through `train.ddp.sync_gradients`,
   bucketed (async, overlapped) vs `RAY_TPU_TRAIN_BUCKET_DDP=0`
   (legacy single synchronous allreduce), same seed, several bucket
   sizes. The headline is p50 of the slowest rank per sync — the
   gang-blocking quantity a train step actually pays.
3. **ZeRO sharded mode** (`--mode reducescatter`, round 19): the same
   grad tree synced with ``mode="reducescatter"`` vs the allreduce
   mode — per-sync wall time AND actual wire bytes per rank (the
   ``ray_tpu_collective_wire_bytes_total`` counter; at world 2 the
   pairwise reducescatter pushes HALF the allreduce's bytes) — plus
   the full optimizer step: ``ZeroOptimizer`` (reducescatter + shard
   adam + async allgather) vs legacy (allreduce + full-vector adam),
   with per-rank optimizer-state bytes for both (the O(model/world)
   fold is the point, the step-time parity is the guardrail).

Sizing note: the whole sweep must fit the node's shm store
(`object_store_memory`); a single-op sync of G bytes stages ~G/2 of
segments per rank concurrently, so keep 2 x grads + segments well
under the store size (the harness uses 64 MB grads against a 256 MB
store). Past that boundary the store starts evicting ephemeral
segments and ops fail loudly — a real capacity limit, not a perf
cliff.

Usage:
  python benchmarks/ddp_bench.py --json-out BENCH_r15.json
  python benchmarks/ddp_bench.py --total-mb 64 --bucket-mb 2 4 8 \
      --leaves 16 --repeats 7
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sync_actor_cls():
    import numpy as np

    import ray_tpu

    @ray_tpu.remote
    class DdpRank:
        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def grad_sync_bench(self, rank, name, total_bytes, n_leaves,
                            bucket_bytes, bucketed, repeats):
            """Per-sync wall times for one configuration. The grads
            tree is built once (seeded) and reused — sync_gradients
            never mutates its input — so the timed region is exactly
            pack + allreduce + unpack, overlapped or not."""
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = \
                "1" if bucketed else "0"
            from ray_tpu.train import ddp
            from ray_tpu.util import collective as col

            rng = np.random.RandomState(3 + rank)
            per = max(1, int(total_bytes) // 4 // n_leaves)
            grads = {f"w{i:02d}": rng.standard_normal(per)
                     .astype(np.float32) for i in range(n_leaves)}
            ddp.sync_gradients(grads, name,
                               bucket_bytes=bucket_bytes)   # warmup
            col.barrier(name)
            out = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                ddp.sync_gradients(grads, name,
                                   bucket_bytes=bucket_bytes)
                out.append(time.perf_counter() - t0)
            return out

        def bucket_stats(self):
            from ray_tpu.util.metrics import registry_snapshot

            out = {}
            for fam in registry_snapshot():
                if fam["name"].startswith("ray_tpu_train_bucket"):
                    out[fam["name"]] = fam.get("values") or \
                        fam.get("counts")
            return out

        def wire_bytes(self, name):
            """This rank's cumulative pushed wire bytes for `name`,
            keyed by op — delta two reads around a sync to get the
            per-sync wire cost."""
            from ray_tpu.util.metrics import registry_snapshot

            out = {}
            for fam in registry_snapshot():
                if fam["name"] != "ray_tpu_collective_wire_bytes_total":
                    continue
                for v in fam.get("values") or []:
                    if v["tags"].get("group") == name:
                        op = v["tags"].get("op")
                        out[op] = out.get(op, 0.0) + v["value"]
            return out

        def shard_sync_bench(self, rank, name, total_bytes, n_leaves,
                             bucket_bytes, mode, repeats):
            """Per-sync wall times for one mode ("allreduce" |
            "reducescatter") plus the wire-byte delta across the timed
            region — same tree, same buckets, only the sync shape
            changes."""
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = "1"
            from ray_tpu.train import ddp
            from ray_tpu.util import collective as col

            rng = np.random.RandomState(3 + rank)
            per = max(1, int(total_bytes) // 4 // n_leaves)
            grads = {f"w{i:02d}": rng.standard_normal(per)
                     .astype(np.float32) for i in range(n_leaves)}
            ddp.sync_gradients(grads, name, bucket_bytes=bucket_bytes,
                               mode=mode)                    # warmup
            col.barrier(name)
            w0 = self.wire_bytes(name)
            out = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                ddp.sync_gradients(grads, name,
                                   bucket_bytes=bucket_bytes, mode=mode)
                out.append(time.perf_counter() - t0)
            w1 = self.wire_bytes(name)
            wire = sum(w1.values()) - sum(w0.values())
            return {"times": out, "wire_bytes_per_sync": wire / repeats}

        def zero_step_bench(self, rank, name, total_bytes, n_leaves,
                            bucket_bytes, zero, repeats):
            """Full optimizer step: ZeroOptimizer (sharded) vs legacy
            (allreduce + the SAME elementwise adam over the full packed
            buckets). Returns per-step wall times and this rank's
            resident optimizer-state bytes."""
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = "1"
            from ray_tpu.parallel import sharding as sh
            from ray_tpu.train import ddp

            rng = np.random.RandomState(3 + rank)
            per = max(1, int(total_bytes) // 4 // n_leaves)
            params = {f"w{i:02d}": rng.standard_normal(per)
                      .astype(np.float32) for i in range(n_leaves)}
            grads = {f"w{i:02d}": rng.standard_normal(per)
                     .astype(np.float32) for i in range(n_leaves)}
            times = []
            if zero:
                zopt = ddp.ZeroOptimizer(ddp.zero_adam(0.01), name,
                                         bucket_bytes=bucket_bytes)
                params = zopt.step(params, grads)        # warmup + plan
                for _ in range(repeats):
                    t0 = time.perf_counter()
                    params = zopt.step(params, grads)
                    times.append(time.perf_counter() - t0)
                return {"times": times,
                        "state_bytes": zopt.state_bytes(),
                        "replicated_bytes":
                            zopt.replicated_state_bytes()}
            opt = ddp.zero_adam(0.01)
            leaves, treedef = sh.flatten_tree(params)
            plan = sh.plan_buckets(leaves, bucket_bytes)
            state = [opt.init(sum(int(np.asarray(leaves[i]).size)
                                  for i in b), np.dtype(np.float32))
                     for b in plan]
            step_no = 0

            def one_step(params):
                synced = ddp.sync_gradients(grads, name,
                                            bucket_bytes=bucket_bytes)
                gleaves, _ = sh.flatten_tree(synced)
                pleaves, _ = sh.flatten_tree(params)
                out = [None] * len(pleaves)
                for b, indices in enumerate(plan):
                    pflat = sh.pack_bucket(pleaves, indices)
                    gflat = sh.pack_bucket(
                        [np.asarray(g) for g in gleaves], indices)
                    pflat = opt.apply(pflat, gflat, state[b], step_no)
                    sh.unpack_bucket(pflat, pleaves, indices, out)
                return sh.unflatten_tree(treedef, out)

            step_no = 1
            params = one_step(params)                    # warmup
            for _ in range(repeats):
                step_no += 1
                t0 = time.perf_counter()
                params = one_step(params)
                times.append(time.perf_counter() - t0)
            return {"times": times,
                    "state_bytes": float(sum(
                        arr.nbytes for st in state
                        for arr in st.values())),
                    "replicated_bytes": float(sum(
                        arr.nbytes for st in state
                        for arr in st.values()))}

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

    return DdpRank


def run_grad_sync(world: int, total_bytes: int, n_leaves: int,
                  bucket_mbs: list[float], repeats: int) -> list[dict]:
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, world),
                 object_store_memory=256 * 1024 * 1024)
    name = "ddp_bench"
    try:
        DdpRank = _sync_actor_cls()
        actors = [DdpRank.options(num_cpus=0).remote()
                  for _ in range(world)]
        ray_tpu.get([a.join.remote(world, i, name)
                     for i, a in enumerate(actors)], timeout=120)

        def one(bucketed: bool, bucket_bytes: int) -> dict:
            per_rank = ray_tpu.get(
                [a.grad_sync_bench.remote(i, name, total_bytes,
                                          n_leaves, bucket_bytes,
                                          bucketed, repeats)
                 for i, a in enumerate(actors)], timeout=1800)
            per_op = [max(ts) for ts in zip(*per_rank)]
            p50 = sorted(per_op)[len(per_op) // 2]
            return {
                "phase": "train_grad_sync", "world": world,
                "total_bytes": total_bytes, "leaves": n_leaves,
                "bucketed": bucketed, "bucket_bytes": bucket_bytes,
                "p50_sync_s": round(p50, 6),
                "best_sync_s": round(min(per_op), 6),
                "mean_sync_s": round(sum(per_op) / len(per_op), 6),
                "p50_effective_GBps": round(
                    total_bytes / p50 / 1e9, 3),
            }

        rows = [one(False, total_bytes)]          # legacy baseline
        print(json.dumps(rows[-1]), flush=True)
        base = rows[0]["p50_sync_s"]
        for mb in bucket_mbs:
            row = one(True, int(mb * 2**20))
            row["p50_speedup_vs_off"] = round(
                base / row["p50_sync_s"], 3)
            rows.append(row)
            print(json.dumps(row), flush=True)
        rows.append({"phase": "bucket_metrics",
                     "stats": ray_tpu.get(
                         actors[0].bucket_stats.remote())})
        ray_tpu.get([a.destroy.remote(name) for a in actors],
                    timeout=60)
        return rows
    finally:
        ray_tpu.shutdown()


def run_zero_sweep(world: int, total_bytes: int, n_leaves: int,
                   bucket_mbs: list[float], repeats: int) -> list[dict]:
    """The --mode reducescatter phase: sharded vs legacy sync shape
    (wall time + wire bytes), then the full sharded vs replicated
    optimizer step (wall time + per-rank state bytes)."""
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, world),
                 object_store_memory=256 * 1024 * 1024)
    name = "zero_bench"
    rows = []
    try:
        DdpRank = _sync_actor_cls()
        actors = [DdpRank.options(num_cpus=0).remote()
                  for _ in range(world)]
        ray_tpu.get([a.join.remote(world, i, name)
                     for i, a in enumerate(actors)], timeout=120)

        def sync_row(mode: str, bucket_bytes: int) -> dict:
            per_rank = ray_tpu.get(
                [a.shard_sync_bench.remote(i, name, total_bytes,
                                           n_leaves, bucket_bytes,
                                           mode, repeats)
                 for i, a in enumerate(actors)], timeout=1800)
            per_op = [max(ts) for ts in
                      zip(*[r["times"] for r in per_rank])]
            p50 = sorted(per_op)[len(per_op) // 2]
            return {
                "phase": "zero_grad_sync", "world": world,
                "total_bytes": total_bytes, "leaves": n_leaves,
                "mode": mode, "bucket_bytes": bucket_bytes,
                "p50_sync_s": round(p50, 6),
                "best_sync_s": round(min(per_op), 6),
                "wire_bytes_per_sync_per_rank": round(sum(
                    r["wire_bytes_per_sync"] for r in per_rank)
                    / world),
            }

        def step_row(zero: bool, bucket_bytes: int) -> dict:
            per_rank = ray_tpu.get(
                [a.zero_step_bench.remote(i, name, total_bytes,
                                          n_leaves, bucket_bytes,
                                          zero, repeats)
                 for i, a in enumerate(actors)], timeout=1800)
            per_op = [max(ts) for ts in
                      zip(*[r["times"] for r in per_rank])]
            p50 = sorted(per_op)[len(per_op) // 2]
            return {
                "phase": "zero_opt_step", "world": world,
                "total_bytes": total_bytes, "leaves": n_leaves,
                "sharded": zero, "bucket_bytes": bucket_bytes,
                "p50_step_s": round(p50, 6),
                "best_step_s": round(min(per_op), 6),
                "opt_state_bytes_per_rank": int(
                    per_rank[0]["state_bytes"]),
                "replicated_state_bytes": int(
                    per_rank[0]["replicated_bytes"]),
            }

        for mb in bucket_mbs:
            bucket_bytes = int(mb * 2**20)
            base = sync_row("allreduce", bucket_bytes)
            rows.append(base)
            print(json.dumps(base), flush=True)
            row = sync_row("reducescatter", bucket_bytes)
            row["wire_fraction_vs_allreduce"] = round(
                row["wire_bytes_per_sync_per_rank"]
                / max(1, base["wire_bytes_per_sync_per_rank"]), 3)
            rows.append(row)
            print(json.dumps(row), flush=True)
        bucket_bytes = int(bucket_mbs[0] * 2**20)
        base = step_row(False, bucket_bytes)
        rows.append(base)
        print(json.dumps(base), flush=True)
        row = step_row(True, bucket_bytes)
        row["p50_step_vs_legacy"] = round(
            row["p50_step_s"] / base["p50_step_s"], 3)
        row["state_fold_vs_replicated"] = round(
            base["replicated_state_bytes"]
            / max(1, row["opt_state_bytes_per_rank"]), 3)
        rows.append(row)
        print(json.dumps(row), flush=True)
        ray_tpu.get([a.destroy.remote(name) for a in actors],
                    timeout=60)
        return rows
    finally:
        ray_tpu.shutdown()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--total-mb", type=float, default=64)
    ap.add_argument("--leaves", type=int, default=16)
    ap.add_argument("--bucket-mb", type=float, nargs="+",
                    default=[2, 4, 8])
    ap.add_argument("--repeats", type=int, default=7)
    ap.add_argument("--async-sizes-mb", type=float, nargs="+",
                    default=[1, 8])
    ap.add_argument("--skip-async", action="store_true",
                    help="skip the handle-overhead phase")
    ap.add_argument("--mode", choices=["allreduce", "reducescatter"],
                    default="allreduce",
                    help="reducescatter adds the ZeRO sharded sweep "
                         "(sync shape + full optimizer step)")
    ap.add_argument("--skip-grad-sync", action="store_true",
                    help="skip the bucketed-vs-legacy grad-sync phase")
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args(argv)

    rows = []
    if not args.skip_async:
        from benchmarks.collective_bench import run_async_sweep

        for r in run_async_sweep(
                args.world,
                [int(mb * 2**20) for mb in args.async_sizes_mb],
                args.repeats):
            rows.append({"phase": "handle_overhead", **r})
    if not args.skip_grad_sync:
        rows += run_grad_sync(args.world, int(args.total_mb * 2**20),
                              args.leaves, args.bucket_mb, args.repeats)
    if args.mode == "reducescatter":
        rows += run_zero_sweep(args.world, int(args.total_mb * 2**20),
                               args.leaves, args.bucket_mb,
                               args.repeats)

    train_rows = [r for r in rows
                  if r.get("phase") == "train_grad_sync"]
    bucketed = [r for r in train_rows if r["bucketed"]]
    if bucketed:
        best = max(bucketed, key=lambda r: r.get("p50_speedup_vs_off", 0))
        print(f"best bucketed config: {best['bucket_bytes'] // 2**20}MB "
              f"buckets, {best['p50_speedup_vs_off']}x vs unbucketed "
              f"({best['p50_sync_s'] * 1e3:.1f}ms vs "
              f"{train_rows[0]['p50_sync_s'] * 1e3:.1f}ms p50)",
              file=sys.stderr)
    if args.json_out:
        record = {"harness": "benchmarks/ddp_bench.py",
                  "argv": list(argv) if argv is not None
                  else sys.argv[1:],
                  "rows": rows}
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json_out} ({len(rows)} rows)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
