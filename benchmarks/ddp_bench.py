"""Bucketed-DDP gradient-sync benchmark — PERF.md round 15 artifact.

Two phases, one JSON artifact (BENCH_r15.json):

1. **handle overhead** (`collective_bench.run_async_sweep`): sync
   allreduce baseline vs `allreduce_async` at submission windows 1 and
   4 — window 1 isolates the per-op cost of the handle plane (submit +
   issue-thread handoff + handle wakeup), deeper windows measure the
   pipelined submission path bucketed DDP rides.
2. **train grad-sync step** — the acceptance measurement: a 2-worker
   gang syncing a comm-bound grad pytree (default 64 MB, far past the
   8 MB BENCH_r06/r08 regime) through `train.ddp.sync_gradients`,
   bucketed (async, overlapped) vs `RAY_TPU_TRAIN_BUCKET_DDP=0`
   (legacy single synchronous allreduce), same seed, several bucket
   sizes. The headline is p50 of the slowest rank per sync — the
   gang-blocking quantity a train step actually pays.

Sizing note: the whole sweep must fit the node's shm store
(`object_store_memory`); a single-op sync of G bytes stages ~G/2 of
segments per rank concurrently, so keep 2 x grads + segments well
under the store size (the harness uses 64 MB grads against a 256 MB
store). Past that boundary the store starts evicting ephemeral
segments and ops fail loudly — a real capacity limit, not a perf
cliff.

Usage:
  python benchmarks/ddp_bench.py --json-out BENCH_r15.json
  python benchmarks/ddp_bench.py --total-mb 64 --bucket-mb 2 4 8 \
      --leaves 16 --repeats 7
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sync_actor_cls():
    import numpy as np

    import ray_tpu

    @ray_tpu.remote
    class DdpRank:
        def join(self, world, rank, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, "host", name)
            return rank

        def grad_sync_bench(self, rank, name, total_bytes, n_leaves,
                            bucket_bytes, bucketed, repeats):
            """Per-sync wall times for one configuration. The grads
            tree is built once (seeded) and reused — sync_gradients
            never mutates its input — so the timed region is exactly
            pack + allreduce + unpack, overlapped or not."""
            os.environ["RAY_TPU_TRAIN_BUCKET_DDP"] = \
                "1" if bucketed else "0"
            from ray_tpu.train import ddp
            from ray_tpu.util import collective as col

            rng = np.random.RandomState(3 + rank)
            per = max(1, int(total_bytes) // 4 // n_leaves)
            grads = {f"w{i:02d}": rng.standard_normal(per)
                     .astype(np.float32) for i in range(n_leaves)}
            ddp.sync_gradients(grads, name,
                               bucket_bytes=bucket_bytes)   # warmup
            col.barrier(name)
            out = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                ddp.sync_gradients(grads, name,
                                   bucket_bytes=bucket_bytes)
                out.append(time.perf_counter() - t0)
            return out

        def bucket_stats(self):
            from ray_tpu.util.metrics import registry_snapshot

            out = {}
            for fam in registry_snapshot():
                if fam["name"].startswith("ray_tpu_train_bucket"):
                    out[fam["name"]] = fam.get("values") or \
                        fam.get("counts")
            return out

        def destroy(self, name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(name)
            return True

    return DdpRank


def run_grad_sync(world: int, total_bytes: int, n_leaves: int,
                  bucket_mbs: list[float], repeats: int) -> list[dict]:
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, world),
                 object_store_memory=256 * 1024 * 1024)
    name = "ddp_bench"
    try:
        DdpRank = _sync_actor_cls()
        actors = [DdpRank.options(num_cpus=0).remote()
                  for _ in range(world)]
        ray_tpu.get([a.join.remote(world, i, name)
                     for i, a in enumerate(actors)], timeout=120)

        def one(bucketed: bool, bucket_bytes: int) -> dict:
            per_rank = ray_tpu.get(
                [a.grad_sync_bench.remote(i, name, total_bytes,
                                          n_leaves, bucket_bytes,
                                          bucketed, repeats)
                 for i, a in enumerate(actors)], timeout=1800)
            per_op = [max(ts) for ts in zip(*per_rank)]
            p50 = sorted(per_op)[len(per_op) // 2]
            return {
                "phase": "train_grad_sync", "world": world,
                "total_bytes": total_bytes, "leaves": n_leaves,
                "bucketed": bucketed, "bucket_bytes": bucket_bytes,
                "p50_sync_s": round(p50, 6),
                "best_sync_s": round(min(per_op), 6),
                "mean_sync_s": round(sum(per_op) / len(per_op), 6),
                "p50_effective_GBps": round(
                    total_bytes / p50 / 1e9, 3),
            }

        rows = [one(False, total_bytes)]          # legacy baseline
        print(json.dumps(rows[-1]), flush=True)
        base = rows[0]["p50_sync_s"]
        for mb in bucket_mbs:
            row = one(True, int(mb * 2**20))
            row["p50_speedup_vs_off"] = round(
                base / row["p50_sync_s"], 3)
            rows.append(row)
            print(json.dumps(row), flush=True)
        rows.append({"phase": "bucket_metrics",
                     "stats": ray_tpu.get(
                         actors[0].bucket_stats.remote())})
        ray_tpu.get([a.destroy.remote(name) for a in actors],
                    timeout=60)
        return rows
    finally:
        ray_tpu.shutdown()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--total-mb", type=float, default=64)
    ap.add_argument("--leaves", type=int, default=16)
    ap.add_argument("--bucket-mb", type=float, nargs="+",
                    default=[2, 4, 8])
    ap.add_argument("--repeats", type=int, default=7)
    ap.add_argument("--async-sizes-mb", type=float, nargs="+",
                    default=[1, 8])
    ap.add_argument("--skip-async", action="store_true",
                    help="skip the handle-overhead phase")
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args(argv)

    rows = []
    if not args.skip_async:
        from benchmarks.collective_bench import run_async_sweep

        for r in run_async_sweep(
                args.world,
                [int(mb * 2**20) for mb in args.async_sizes_mb],
                args.repeats):
            rows.append({"phase": "handle_overhead", **r})
    rows += run_grad_sync(args.world, int(args.total_mb * 2**20),
                          args.leaves, args.bucket_mb, args.repeats)

    train_rows = [r for r in rows
                  if r.get("phase") == "train_grad_sync"]
    bucketed = [r for r in train_rows if r["bucketed"]]
    if bucketed:
        best = max(bucketed, key=lambda r: r.get("p50_speedup_vs_off", 0))
        print(f"best bucketed config: {best['bucket_bytes'] // 2**20}MB "
              f"buckets, {best['p50_speedup_vs_off']}x vs unbucketed "
              f"({best['p50_sync_s'] * 1e3:.1f}ms vs "
              f"{train_rows[0]['p50_sync_s'] * 1e3:.1f}ms p50)",
              file=sys.stderr)
    if args.json_out:
        record = {"harness": "benchmarks/ddp_bench.py",
                  "argv": list(argv) if argv is not None
                  else sys.argv[1:],
                  "rows": rows}
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json_out} ({len(rows)} rows)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
