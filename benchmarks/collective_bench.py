"""Collective bus-bandwidth harness — BASELINE.md north-star metric #2.

Reference shape: python/ray/util/collective/examples/ (allreduce/p2p
latency + bandwidth scripts run at several payload sizes). Reports
algorithm bandwidth (payload / wall time) and NCCL-convention bus
bandwidth for each (backend, op, size):

    allreduce:      busbw = algbw * 2(n-1)/n
    allgather:      busbw = algbw *  (n-1)/n
    reducescatter:  busbw = algbw *  (n-1)/n

Size semantics follow nccl-tests so backends are comparable: `size` is
the PER-RANK input buffer for allreduce and reducescatter, and the TOTAL
gathered output (per-rank input = size/n) for allgather. algbw = size/t
in all cases.

Backends:
  host       N actor processes, ring/tree collectives over sockets
             (ray_tpu.util.collective "host" backend)
  xla-local  shard_map collectives on the in-process device mesh
             (8 virtual CPU devices under the test env; real chips on
             TPU hosts) — the compiled-program path that rides ICI
  tpu        xla-local, but only after probing the TPU tunnel in a
             subprocess (it can hang for hours); single-chip worlds are
             reported with n=1 so the degenerate case is explicit

Usage:
  python benchmarks/collective_bench.py --backend host --world 2 \
      --sizes-mb 1 8 64 --repeats 5
  python benchmarks/collective_bench.py --backend xla-local

Each result prints as ONE JSON line; a summary table follows on stderr.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OPS = ("allreduce", "allgather", "reducescatter")


def bus_factor(op: str, n: int) -> float:
    if n <= 1:
        return 1.0
    if op == "allreduce":
        return 2.0 * (n - 1) / n
    return (n - 1) / n


def emit(result: dict):
    print(json.dumps(result), flush=True)


# --------------------------------------------------------------- host backend

# conservative per-format per-hop quantization step, relative to the
# running partial sum's absmax (bf16: half ULP of an 8-bit mantissa;
# int8: half a step of a 127-level block scale)
WIRE_Q = {"bf16": 2.0 ** -8, "int8": 1.0 / 254.0}


def _host_bench_actor_cls():
    import numpy as np

    import ray_tpu
    from ray_tpu.util.collective import CollectiveActorMixin

    @ray_tpu.remote
    class BenchRank(CollectiveActorMixin):
        def wire_error(self, size_bytes: int, fmt: str) -> dict:
            """Measured allreduce error under the active wire format,
            against a locally reconstructed exact (float64) oracle.
            Returns the max-abs error and the DOCUMENTED bound: at most
            `world` quantized hops (world-1 reduce steps + the final
            chunk's own encode), each within q_fmt of the running
            partial's absmax, which is itself bounded by the sum of the
            ranks' input absmaxes."""
            from ray_tpu.util import collective as col

            n = col.get_collective_group_size()
            rank = col.get_rank()
            elems = max(1, size_bytes // 4)
            ins = [np.random.RandomState(1000 + r)
                   .standard_normal(elems).astype(np.float32)
                   for r in range(n)]
            got = np.asarray(col.allreduce(ins[rank])).astype(np.float64)
            exact = np.zeros(elems, np.float64)
            for x in ins:
                exact += x
            err = float(np.abs(got - exact).max())
            absmax_sum = float(sum(np.abs(x).max() for x in ins))
            q = WIRE_Q.get(fmt, 0.0)
            return {"max_abs_err": err,
                    "err_bound": n * q * absmax_sum,
                    "absmax_sum": absmax_sum}

        def bench_async(self, size_bytes: int, repeats: int,
                        window: int) -> list:
            """Per-op wall times of `window` async allreduces submitted
            back-to-back and waited together. window=1 vs the sync
            `bench` rows is the pure handle overhead (submit + issue-
            thread handoff + handle wakeup); larger windows measure the
            pipelined submission path the bucketed-DDP plane rides."""
            from ray_tpu.util import collective as col

            elems = max(1, size_bytes // 4)
            arr = np.ones(elems, dtype=np.float32)
            col.allreduce_async(arr).result(120)       # warmup
            col.barrier()
            out = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                handles = [col.allreduce_async(arr)
                           for _ in range(window)]
                for h in handles:
                    h.result(600)
                out.append((time.perf_counter() - t0) / window)
            return out

        def bench(self, op: str, size_bytes: int, repeats: int) -> list:
            """Returns per-op wall times (seconds), one per repeat —
            the caller derives mean (headline, comparable to earlier
            rounds) plus p50/min (steady-state vs scheduler-outlier
            split on shared boxes)."""
            from ray_tpu.util import collective as col

            n = col.get_collective_group_size()
            elems = max(1, size_bytes // 4)
            if op == "reducescatter":
                # per-rank input = size, divisible into n shards
                elems = max(n, elems - elems % n)
            elif op == "allgather":
                # nccl-tests convention: size = total gathered output,
                # so each rank contributes size/n
                elems = max(1, elems // n)
            arr = np.ones(elems, dtype=np.float32)
            fn = {
                "allreduce": lambda: col.allreduce(arr),
                "allgather": lambda: col.allgather(arr),
                "reducescatter": lambda: col.reducescatter(arr),
            }[op]
            fn()                      # warmup
            col.barrier()             # synchronized start
            out = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                fn()
                out.append(time.perf_counter() - t0)
            return out

    return BenchRank


def run_host(world: int, sizes: list[int], repeats: int,
             extra: dict | None = None,
             wire_fmt: str | None = None) -> list[dict]:
    import ray_tpu
    from ray_tpu.util import collective as col

    ray_tpu.init(num_cpus=max(4, world),
                 object_store_memory=256 * 1024 * 1024)
    try:
        BenchRank = _host_bench_actor_cls()
        actors = [BenchRank.options(num_cpus=0).remote()
                  for _ in range(world)]
        col.create_collective_group(actors, world, list(range(world)),
                                    backend="host")
        out = []
        for op in OPS:
            for size in sizes:
                err_stats = None
                if wire_fmt is not None and op == "allreduce":
                    # measured quantization error + documented bound,
                    # same cluster/knobs as the timed rows (worst rank)
                    errs = ray_tpu.get(
                        [a.wire_error.remote(size, wire_fmt)
                         for a in actors], timeout=600)
                    err_stats = max(errs, key=lambda e: e["max_abs_err"])
                per_rank = ray_tpu.get(
                    [a.bench.remote(op, size, repeats) for a in actors],
                    timeout=1800)
                # slowest rank bounds the op; mean is the headline
                # (comparable to earlier rounds), p50/min expose the
                # scheduler-outlier share on shared dev boxes
                per_op = [max(ts) for ts in zip(*per_rank)]
                dt = sum(per_op) / len(per_op)
                p50 = sorted(per_op)[len(per_op) // 2]
                best = min(per_op)
                bf = bus_factor(op, world)
                algbw = size / dt / 1e9
                out.append({
                    "backend": "host", "op": op, "size_bytes": size,
                    "world": world, "time_s": round(dt, 6),
                    "algbw_GBps": round(algbw, 4),
                    "busbw_GBps": round(algbw * bf, 4),
                    "p50_busbw_GBps": round(size / p50 / 1e9 * bf, 4),
                    "best_busbw_GBps": round(size / best / 1e9 * bf, 4),
                    **({"quant_max_abs_err": err_stats["max_abs_err"],
                        "quant_err_bound": err_stats["err_bound"]}
                       if err_stats else {}),
                    **(extra or {}),
                })
                emit(out[-1])
        return out
    finally:
        ray_tpu.shutdown()


def run_host_sweep(world: int, sizes: list[int], repeats: int,
                   segment_sweep: list[int] | None,
                   pipeline: str | None) -> list[dict]:
    """Host-backend runs across the pipeline knobs. Each configuration
    gets a fresh cluster (the knobs ride env vars that member worker
    processes inherit at spawn), and each row records the knob values so
    the JSON artifact is self-describing."""
    if pipeline is not None:
        os.environ["RAY_TPU_COLLECTIVE_PIPELINE"] = \
            "1" if pipeline == "on" else "0"
    pipe_on = os.environ.get("RAY_TPU_COLLECTIVE_PIPELINE", "1") != "0"
    rows = []
    for seg in (segment_sweep or [None]):
        if seg is not None:
            os.environ["RAY_TPU_COLLECTIVE_SEGMENT_BYTES"] = str(int(seg))
        from ray_tpu._private.config import get_config

        rows += run_host(world, sizes, repeats, extra={
            "pipeline": pipe_on,
            "segment_bytes": int(get_config("collective_segment_bytes")),
        })
    return rows


def run_wire_sweep(world: int, sizes: list[int], repeats: int,
                   wire_dtypes: list[str], keep_shm: bool) -> list[dict]:
    """Host-backend sweep across wire formats, one fresh cluster per
    format, ALWAYS anchored by a same-run `off` baseline. Unless
    --wire-shm is passed, the whole sweep (baseline included) runs with
    the same-node shm transport off: quantization is an INTER-host wire
    feature — in production the intra-host hierarchy keeps same-host
    hops exact, so the socket path is the wire a cross-host deployment
    actually quantizes, and comparing both configs on it is the
    apples-to-apples measurement. Rows record wire_dtype +
    collective_shm so the artifact is self-describing, and allreduce
    rows carry the measured max-abs error against an exact float64
    oracle plus the documented bound (world * q_fmt * sum of per-rank
    input absmaxes)."""
    fmts = list(wire_dtypes)
    if "off" not in fmts:
        fmts.insert(0, "off")
    else:
        fmts.sort(key=lambda f: f != "off")   # baseline first
    if not keep_shm:
        os.environ["RAY_TPU_COLLECTIVE_SHM"] = "0"
    rows = []
    for fmt in fmts:
        os.environ["RAY_TPU_COLLECTIVE_WIRE_DTYPE"] = fmt
        from ray_tpu._private.config import get_config

        rows += run_host(
            world, sizes, repeats,
            extra={
                "wire_dtype": fmt,
                "collective_shm": bool(get_config("collective_shm")),
                "segment_bytes":
                    int(get_config("collective_segment_bytes")),
                "quant_block":
                    int(get_config("collective_quant_block")),
            },
            wire_fmt=fmt)
    baseline = {(r["op"], r["size_bytes"]): r for r in rows
                if r["wire_dtype"] == "off"}
    for r in rows:
        base = baseline.get((r["op"], r["size_bytes"]))
        if base is not None and r["wire_dtype"] != "off":
            r["p50_speedup_vs_off"] = round(
                r["p50_busbw_GBps"] / max(base["p50_busbw_GBps"], 1e-9), 3)
    return rows


def run_async_sweep(world: int, sizes: list[int], repeats: int,
                    windows: list[int] | None = None) -> list[dict]:
    """--async: handle-overhead sweep. For each size: a sync-allreduce
    baseline, then async submissions at each window depth (window=1
    isolates the per-op handle overhead; deeper windows measure the
    pipelined submission path). One cluster for the whole sweep — the
    knobs don't change between rows."""
    import ray_tpu
    from ray_tpu.util import collective as col

    windows = windows or [1, 4]
    ray_tpu.init(num_cpus=max(4, world),
                 object_store_memory=256 * 1024 * 1024)
    try:
        BenchRank = _host_bench_actor_cls()
        actors = [BenchRank.options(num_cpus=0).remote()
                  for _ in range(world)]
        col.create_collective_group(actors, world, list(range(world)),
                                    backend="host")
        rows = []
        for size in sizes:
            per_rank = ray_tpu.get(
                [a.bench.remote("allreduce", size, repeats)
                 for a in actors], timeout=1800)
            sync_ops = [max(ts) for ts in zip(*per_rank)]
            sync_p50 = sorted(sync_ops)[len(sync_ops) // 2]
            rows.append({
                "backend": "host", "op": "allreduce", "mode": "sync",
                "size_bytes": size, "world": world,
                "p50_time_s": round(sync_p50, 6),
                "p50_busbw_GBps": round(
                    size / sync_p50 / 1e9
                    * bus_factor("allreduce", world), 4),
            })
            emit(rows[-1])
            for window in windows:
                per_rank = ray_tpu.get(
                    [a.bench_async.remote(size, repeats, window)
                     for a in actors], timeout=1800)
                per_op = [max(ts) for ts in zip(*per_rank)]
                p50 = sorted(per_op)[len(per_op) // 2]
                row = {
                    "backend": "host", "op": "allreduce",
                    "mode": f"async_w{window}", "size_bytes": size,
                    "world": world, "p50_time_s": round(p50, 6),
                    "p50_busbw_GBps": round(
                        size / p50 / 1e9
                        * bus_factor("allreduce", world), 4),
                }
                if window == 1:
                    row["handle_overhead_us"] = round(
                        (p50 - sync_p50) * 1e6, 1)
                rows.append(row)
                emit(row)
        return rows
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------- xla-local backend

def run_xla_local(sizes: list[int], repeats: int,
                  force_cpu: bool) -> list[dict]:
    if force_cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(devices, ("x",))
    out = []

    def smap(fn, in_specs, out_specs):
        # replication of e.g. tiled all_gather output isn't statically
        # inferred; the kwarg disabling the check was renamed across jax
        # versions (check_rep -> check_vma)
        for kw in ({"check_vma": False}, {"check_rep": False}, {}):
            try:
                return shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
            except TypeError:
                continue
        raise RuntimeError("shard_map construction failed")

    def timed(fn, x):
        y = fn(x)
        jnp.asarray(y).block_until_ready()   # warmup + compile
        # On the axon tunnel block_until_ready returns early; a scalar
        # fetch is the true barrier (verify-skill note). Cheap on CPU too.
        float(jnp.ravel(y)[0])
        t0 = time.perf_counter()
        for _ in range(repeats):
            y = fn(x)
        float(jnp.ravel(y)[0])
        return (time.perf_counter() - t0) / repeats

    for op in OPS:
        for size in sizes:
            if op == "allgather":
                # size = total gathered output; the global array IS the
                # output, each device holds size/n
                elems = max(n, (size // 4) - (size // 4) % n)
            else:
                # size = per-rank input: global array = n * size so each
                # device's shard is the full per-rank buffer
                elems = n * max(1, size // 4)
            x = jnp.ones((elems,), jnp.float32)

            if op == "allreduce":
                f = smap(lambda a: jax.lax.psum(a, "x"),
                         in_specs=P("x"), out_specs=P())
            elif op == "allgather":
                f = smap(lambda a: jax.lax.all_gather(a, "x", tiled=True),
                         in_specs=P("x"), out_specs=P())
            else:  # reducescatter
                f = smap(lambda a: jax.lax.psum_scatter(a, "x", tiled=True),
                         in_specs=P("x"), out_specs=P("x"))
            f = jax.jit(f)
            dt = timed(f, x)
            algbw = size / dt / 1e9
            out.append({
                "backend": "xla", "op": op, "size_bytes": size,
                "world": n, "time_s": round(dt, 6),
                "algbw_GBps": round(algbw, 4),
                "busbw_GBps": round(algbw * bus_factor(op, n), 4),
                "platform": devices[0].platform,
            })
            emit(out[-1])
    return out


# ----------------------------------------------------------------- tpu gating

def tpu_reachable(timeout_s: float = 120.0) -> bool:
    """Subprocess probe: a hung axon tunnel blocks jax.devices() forever
    (shared helper; same guard as bench.py)."""
    from ray_tpu._private.tpu_probe import tpu_reachable_once

    return tpu_reachable_once(timeout_s)


def summarize(rows: list[dict]):
    if not rows:
        return
    hdr = f"{'backend':8} {'op':14} {'size':>10} {'n':>3} " \
          f"{'algbw GB/s':>11} {'busbw GB/s':>11}"
    print("\n" + hdr, file=sys.stderr)
    print("-" * len(hdr), file=sys.stderr)
    for r in rows:
        if "algbw_GBps" not in r:          # --async rows: p50-only
            print(f"{r['backend']:8} {r['op'] + ':' + r['mode']:14} "
                  f"{r['size_bytes'] / 2**20:>8.1f}MB {r['world']:>3} "
                  f"{'':>11} {r['p50_busbw_GBps']:>11.3f}",
                  file=sys.stderr)
            continue
        print(f"{r['backend']:8} {r['op']:14} "
              f"{r['size_bytes'] / 2**20:>8.1f}MB {r['world']:>3} "
              f"{r['algbw_GBps']:>11.3f} {r['busbw_GBps']:>11.3f}",
              file=sys.stderr)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="host",
                    choices=["host", "xla-local", "tpu"])
    ap.add_argument("--world", type=int, default=2,
                    help="actor count (host backend)")
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 8, 64])
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--segment-bytes", type=int, nargs="+", default=None,
                    help="host backend: sweep collective_segment_bytes "
                         "(one fresh cluster per value)")
    ap.add_argument("--pipeline", choices=["on", "off"], default=None,
                    help="host backend: force the pipelined data path "
                         "on/off (default: env/config)")
    ap.add_argument("--wire-dtype", nargs="+", default=None,
                    choices=["off", "bf16", "int8"],
                    help="host backend: sweep block-quantized wire "
                         "formats (a same-run `off` baseline is always "
                         "included; runs the socket wire — the path "
                         "inter-host traffic quantizes — unless "
                         "--wire-shm) and record measured quantization "
                         "error vs an exact oracle")
    ap.add_argument("--wire-shm", action="store_true",
                    help="with --wire-dtype: keep the same-node shm "
                         "segment transport on instead of measuring "
                         "the socket wire")
    ap.add_argument("--async", dest="async_mode", action="store_true",
                    help="host backend: async handle-overhead sweep — "
                         "sync allreduce baseline vs allreduce_async "
                         "at --async-windows submission depths")
    ap.add_argument("--async-windows", type=int, nargs="+",
                    default=[1, 4],
                    help="submission window depths for --async")
    ap.add_argument("--json-out", default=None,
                    help="write all rows as one machine-readable JSON "
                         "record (busbw artifact, e.g. BENCH_r06.json)")
    args = ap.parse_args(argv)
    sizes = [int(mb * 2**20) for mb in args.sizes_mb]

    if args.async_mode and args.backend != "host":
        ap.error("--async requires --backend host (async handles are a "
                 "host-backend feature)")
    if args.async_mode and args.wire_dtype:
        ap.error("--async and --wire-dtype are separate sweeps — run "
                 "them as two invocations")
    if args.backend == "host" and args.async_mode:
        rows = run_async_sweep(args.world, sizes, args.repeats,
                               args.async_windows)
    elif args.backend == "host" and args.wire_dtype:
        rows = run_wire_sweep(args.world, sizes, args.repeats,
                              args.wire_dtype, args.wire_shm)
    elif args.backend == "host":
        rows = run_host_sweep(args.world, sizes, args.repeats,
                              args.segment_bytes, args.pipeline)
    elif args.backend == "xla-local":
        rows = run_xla_local(sizes, args.repeats, force_cpu=True)
    else:  # tpu
        if not tpu_reachable():
            emit({"backend": "tpu", "skipped": True,
                  "reason": "tunnel unreachable"})
            return 0
        rows = run_xla_local(sizes, args.repeats, force_cpu=False)
    summarize(rows)
    if args.json_out:
        record = {
            "harness": "benchmarks/collective_bench.py",
            "argv": list(argv) if argv is not None else sys.argv[1:],
            "rows": rows,
        }
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json_out} ({len(rows)} rows)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
