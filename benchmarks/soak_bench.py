"""Cluster-scale soak bench — rounds 12/13 (BENCH_r12/BENCH_r13.json).

Stands up ``RAY_TPU_SOAK_NODES`` (default 100) simulated raylets
(`ray_tpu/_private/sim_cluster.py`: real GCS registration/heartbeat/
pubsub, no workers) and measures the control plane under seeded chaos:

- **fanout**: a simultaneous ~10% mass kill (`kill_node:*.mass_kill:
  p0.1`), death-feed fanout latency per (survivor, death) pair —
  p50/p99 with the coalescing fix OFF (`gcs_death_coalesce_window_s=0`,
  the pre-PR-12 per-death sweep+broadcast) vs ON. The GCS carries a
  populated object-location table and live heartbeat/lease traffic, so
  the per-death locked sweep costs what it costs in production.
- **restart**: SIGKILL the (subprocess) GCS mid-death-storm with live
  lease traffic; measure the reconvergence window (alive-set equals
  survivors + every subscription healed via the probe publish) and
  assert ZERO lost accepted leases and no survivor missing a death.
- **determinism**: the same seed replays a byte-identical chaos
  journal.
- **multitenant** (round 13): 3 competing jobs (batch pri 0 / train
  pri 5 / serve pri 10, each quota-capped) creating gangs against the
  same 100 nodes under seeded ``preempt_job`` storms + node kills —
  measures high-priority time-to-placement on a full cluster (the
  preemption path end to end) and asserts zero quota violations in
  every ``summarize_jobs`` sample plus a byte-identical journal
  across two runs.

Usage::

    RAY_TPU_SOAK_NODES=100 python benchmarks/soak_bench.py \
        --json-out BENCH_r13.json
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ray_tpu._private import fault_injection as fi  # noqa: E402


def _pct(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def _populate_objects(cluster, n_objects: int):
    """Give the GCS a realistically non-empty object-location table so
    the per-death owned-value sweep has real work (the O(objects) path
    the batch fix collapses from k sweeps to one)."""
    from ray_tpu._private.protocol import RpcClient

    client = RpcClient(cluster.gcs_addr, timeout=30.0)
    try:
        n_nodes = len(cluster.raylets)
        for i in range(n_objects):
            oid = b"soakobj-%08d" % i
            node = cluster.raylets[i % n_nodes].node_id
            client.call("add_object_location", object_id=oid,
                        node_id=node, size=1024)
    finally:
        client.close()


def fanout_phase(nodes: int, seed: int, coalesce: bool,
                 n_objects: int, verbose=print) -> dict:
    from ray_tpu._private.sim_cluster import SimCluster

    os.environ["RAY_TPU_GCS_DEATH_COALESCE_WINDOW_S"] = (
        "0.05" if coalesce else "0")
    fi.install(seed, "kill_node:*.mass_kill:p0.1")
    cluster = SimCluster(n_nodes=nodes, tick_interval=0.05,
                         poll_timeout=2.0).start()
    try:
        _populate_objects(cluster, n_objects)
        cluster.run_ticks(3, leases_every=2)
        cluster.mass_consult("mass_kill")
        t0 = cluster.metrics["mass_kill_initiated_at"]
        killed = cluster.dead_ids()
        cluster.run_ticks(4, leases_every=2)
        conv = cluster.wait_converged(timeout=45.0)
        lat = cluster.fanout_latencies(t0, killed)
        leases = cluster.verify_leases()
        st = cluster.gcs_call("debug_state")

        def _ms(v):
            # a p0.1 schedule can legitimately kill ZERO nodes at small
            # fleet sizes — report a degenerate phase, don't crash
            return round(v * 1e3, 2) if v is not None else None

        out = {
            "coalesce": coalesce,
            "killed": len(killed),
            "survivors": len(cluster.survivors()),
            "pairs_observed": len(lat),
            "pairs_expected": len(killed) * len(cluster.survivors()),
            "fanout_p50_ms": _ms(_pct(lat, 0.50)),
            "fanout_p99_ms": _ms(_pct(lat, 0.99)),
            "fanout_max_ms": _ms(max(lat) if lat else None),
            "reconvergence": conv,
            "lost_leases": len(leases["lost"]),
            "death_batches": st["death_batches"],
            "max_death_batch": st["max_death_batch"],
            "journal_sha256": hashlib.sha256(
                cluster.journal_text().encode()).hexdigest(),
        }
        verbose(f"  fanout[coalesce={coalesce}] killed={out['killed']} "
                f"p50={out['fanout_p50_ms']}ms "
                f"p99={out['fanout_p99_ms']}ms "
                f"converged={conv['converged']} "
                f"lost_leases={out['lost_leases']}")
        return out
    finally:
        cluster.stop()
        fi.uninstall()
        del os.environ["RAY_TPU_GCS_DEATH_COALESCE_WINDOW_S"]


def restart_phase(nodes: int, seed: int, verbose=print) -> dict:
    from ray_tpu._private.sim_cluster import SimCluster

    fi.install(seed, "kill_node:*.mass_kill:p0.1;"
                     "flap_node:*.flap_check:p0.05:400")
    store = os.path.join(tempfile.mkdtemp(prefix="soak_gcs_"), "gcs.db")
    cluster = SimCluster(n_nodes=nodes, tick_interval=0.05,
                         poll_timeout=2.0, gcs="subprocess",
                         store_path=store).start()
    try:
        cluster.run_ticks(3, leases_every=2)
        cluster.mass_consult("mass_kill")
        cluster.mass_consult("flap_check")
        killed = cluster.dead_ids()
        # the reconnect storm: SIGKILL the GCS mid-storm, bring it back
        # on the same port+store; every surviving client heals with
        # jittered arrival into the bounded admission gate
        t_restart = time.monotonic()
        cluster.restart_gcs(downtime_s=0.3)
        cluster.run_ticks(12, leases_every=3)   # flaps rejoin in here
        conv = cluster.wait_converged(timeout=60.0)
        reconv_s = time.monotonic() - t_restart
        leases = cluster.verify_leases()
        st = cluster.gcs_call("debug_state")
        missing_feeds = [
            r.tag for r in cluster.survivors()
            if not killed <= set(r.deaths_seen)]
        out = {
            "killed": len(killed),
            "flapped": sum(1 for line in cluster.journal
                           if "flap_node" in line and "down_ticks" in
                           line),
            "survivors": len(cluster.survivors()),
            "reconvergence_after_restart_s": round(reconv_s, 3),
            "converged": conv["converged"],
            "probe_healed": conv["probe_healed"],
            "accepted_leases": leases["accepted"],
            "lost_leases": len(leases["lost"]),
            "survivors_missing_deaths": missing_feeds,
            "pubsub_resyncs_served": st["pubsub_resyncs_served"],
            "register_throttled": st["register_throttled"],
            "journal_sha256": hashlib.sha256(
                cluster.journal_text().encode()).hexdigest(),
        }
        out["journal_text"] = cluster.journal_text()
        verbose(f"  restart: killed={out['killed']} "
                f"reconverged in {out['reconvergence_after_restart_s']}s "
                f"leases {out['accepted_leases']}/"
                f"lost {out['lost_leases']} "
                f"resyncs={out['pubsub_resyncs_served']} "
                f"throttled={out['register_throttled']}")
        return out
    finally:
        cluster.stop()
        fi.uninstall()


MT_SCHEDULE = ("preempt_job:train.job_tick:%3:300;"
               "preempt_job:batch.job_tick:%4:300;"
               "kill_node:*.mt_kill:p0.04")


def multitenant_phase(nodes: int, seed: int, verbose=print) -> dict:
    """Round-13 phase: competing quota-capped jobs + seeded preemption
    storms + node kills on one 100-node control plane."""
    from ray_tpu._private.sim_cluster import SimCluster

    os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"] = "0.3"
    fi.install(seed, MT_SCHEDULE)
    cluster = SimCluster(n_nodes=nodes, tick_interval=0.05,
                         poll_timeout=2.0).start()
    try:
        cpus = 4.0 * nodes
        # quotas sum past 100%: batch+train can SATURATE the cluster, so
        # every serve scale-up must go through the preemption path —
        # the latency this phase exists to measure
        cluster.register_job("batch", quota={"CPU": cpus * 0.6},
                             priority=0)
        cluster.register_job("train", quota={"CPU": cpus * 0.5},
                             priority=5)
        cluster.register_job("serve", quota={"CPU": cpus * 0.1},
                             priority=10)
        cluster.run_ticks(2)
        # fill the cluster: batch + train gangs up to (and past) quota —
        # the overflow gangs exercise the quota-block path
        for _ in range(int(cpus * 0.6 / 8) + 2):
            cluster.create_job_pg("batch", n_bundles=4, cpu=2.0)
        for _ in range(int(cpus * 0.5 / 8) + 2):
            cluster.create_job_pg("train", n_bundles=4, cpu=2.0)
        cluster.run_ticks(4)
        cluster.sample_jobs()
        # seeded preemption storm + composed node kills, with serve
        # scale-ups arriving against a full cluster
        placement_waits = []
        for round_n in range(6):
            cluster.jobs_tick()
            if round_n == 2:
                cluster.mass_consult("mt_kill")
            if round_n % 2 == 0:
                pg_id = cluster.create_job_pg("serve", n_bundles=2,
                                              cpu=1.0)
                t0 = time.monotonic()
                deadline = t0 + 20.0
                placed = False
                while time.monotonic() < deadline:
                    snap = cluster.gcs_call("get_placement_group",
                                            pg_id=pg_id)
                    if snap and snap["State"] == "CREATED":
                        placed = True
                        break
                    time.sleep(0.05)
                placement_waits.append(
                    {"placed": placed,
                     "wait_ms": round((time.monotonic() - t0) * 1e3, 1)})
            cluster.run_ticks(3)
            cluster.sample_jobs()
        conv = cluster.wait_converged(timeout=45.0)
        st = cluster.gcs_call("debug_state")
        samples = cluster.metrics.get("job_samples", [])
        waits = [w["wait_ms"] for w in placement_waits if w["placed"]]
        out = {
            "nodes": nodes,
            "killed": len(cluster.dead_ids()),
            "preemptions_fired": st.get("preemptions_fired", 0),
            "quota_rejections": st.get("quota_rejections", 0),
            "pending_pgs_end": st.get("pending_pgs", 0),
            "violations_total": sum(len(s["violations"])
                                    for s in samples),
            "samples": len(samples),
            "serve_placements": placement_waits,
            "serve_placement_p50_ms": _pct(waits, 0.50),
            "serve_placement_max_ms": _pct(waits, 1.0),
            "serve_placed_all": all(w["placed"]
                                    for w in placement_waits),
            "reconvergence": conv,
            "journal_sha256": hashlib.sha256(
                cluster.journal_text().encode()).hexdigest(),
            "journal_text": cluster.journal_text(),
        }
        verbose(f"  multitenant: killed={out['killed']} "
                f"preemptions={out['preemptions_fired']} "
                f"violations={out['violations_total']} "
                f"serve p50 wait={out['serve_placement_p50_ms']}ms "
                f"(all placed: {out['serve_placed_all']})")
        return out
    finally:
        cluster.stop()
        fi.uninstall()
        del os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int,
                    default=int(os.environ.get("RAY_TPU_SOAK_NODES",
                                               "100")))
    ap.add_argument("--seed", type=int, default=12)
    ap.add_argument("--objects", type=int, default=20000,
                    help="object-location rows populating the GCS sweep")
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args()

    print(f"soak bench: {args.nodes} simulated raylets, seed {args.seed}")
    t0 = time.time()
    print("phase 1/6: death-feed fanout, coalescing OFF (pre-fix path)")
    before = fanout_phase(args.nodes, args.seed, coalesce=False,
                          n_objects=args.objects)
    print("phase 2/6: death-feed fanout, coalescing ON")
    after = fanout_phase(args.nodes, args.seed, coalesce=True,
                         n_objects=args.objects)
    print("phase 3/6: GCS restart mid-storm (reconnect herd)")
    restart = restart_phase(args.nodes, args.seed)
    print("phase 4/6: determinism replay (same seed, same journal)")
    replay = restart_phase(args.nodes, args.seed,
                           verbose=lambda *_a, **_k: None)
    journals_equal = (replay["journal_text"] == restart["journal_text"])
    restart.pop("journal_text", None)
    replay.pop("journal_text", None)
    print("phase 5/6: multi-tenant (3 jobs, seeded preemptions + kills)")
    mt = multitenant_phase(args.nodes, args.seed)
    print("phase 6/6: multi-tenant determinism replay")
    mt_replay = multitenant_phase(args.nodes, args.seed,
                                  verbose=lambda *_a, **_k: None)
    mt_journals_equal = (mt_replay["journal_text"] == mt["journal_text"])
    mt.pop("journal_text", None)
    mt_replay.pop("journal_text", None)

    result = {
        "round": 13,
        "bench": "cluster_soak",
        "nodes": args.nodes,
        "seed": args.seed,
        "objects": args.objects,
        "schedule_fanout": "kill_node:*.mass_kill:p0.1",
        "schedule_restart": ("kill_node:*.mass_kill:p0.1;"
                             "flap_node:*.flap_check:p0.05:400"),
        "fanout_before": before,
        "fanout_after": after,
        "fanout_p99_improvement_x": (
            round(before["fanout_p99_ms"] / after["fanout_p99_ms"], 2)
            if before["fanout_p99_ms"] and after["fanout_p99_ms"]
            else None),
        "restart": restart,
        "schedule_multitenant": MT_SCHEDULE,
        "multitenant": mt,
        "determinism": {
            "journals_equal": journals_equal,
            "journal_sha256": restart["journal_sha256"],
            "multitenant_journals_equal": mt_journals_equal,
            "multitenant_journal_sha256": mt["journal_sha256"],
        },
        "acceptance": {
            "zero_quota_violations": mt["violations_total"] == 0,
            "preemptions_fired": mt["preemptions_fired"] > 0,
            "high_pri_always_placed": mt["serve_placed_all"],
            "multitenant_reproducible": mt_journals_equal,
            "zero_lost_leases": (before["lost_leases"] == 0
                                 and after["lost_leases"] == 0
                                 and restart["lost_leases"] == 0),
            "all_subscriptions_healed": (
                restart["probe_healed"]
                and not restart["survivors_missing_deaths"]),
            "reconverged_bounded": restart["converged"],
            "reproducible": journals_equal,
            "fanout_p99_improved": (
                before["fanout_p99_ms"] is not None
                and after["fanout_p99_ms"] is not None
                and before["fanout_p99_ms"] > after["fanout_p99_ms"]),
        },
        "wall_s": round(time.time() - t0, 1),
    }
    print(json.dumps(result["acceptance"], indent=2))
    print(f"fanout p99: {before['fanout_p99_ms']}ms -> "
          f"{after['fanout_p99_ms']}ms "
          f"({result['fanout_p99_improvement_x']}x); "
          f"reconvergence after restart: "
          f"{restart['reconvergence_after_restart_s']}s; "
          f"multitenant: {mt['preemptions_fired']} preemptions, "
          f"{mt['violations_total']} violations, serve placement p50 "
          f"{mt['serve_placement_p50_ms']}ms")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
        print(f"wrote {args.json_out}")
    return 0 if all(result["acceptance"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
