"""Cluster-scale soak bench — rounds 12/13/16 (BENCH_r*.json).

Stands up ``RAY_TPU_SOAK_NODES`` (default 100) simulated raylets
(`ray_tpu/_private/sim_cluster.py`: real GCS registration/heartbeat/
pubsub, no workers) and measures the control plane under seeded chaos:

- **fanout**: a simultaneous ~10% mass kill (`kill_node:*.mass_kill:
  p0.1`), death-feed fanout latency per (survivor, death) pair —
  p50/p99 with the coalescing fix OFF (`gcs_death_coalesce_window_s=0`,
  the pre-PR-12 per-death sweep+broadcast) vs ON. The GCS carries a
  populated object-location table and live heartbeat/lease traffic, so
  the per-death locked sweep costs what it costs in production.
- **restart**: SIGKILL the (subprocess) GCS mid-death-storm with live
  lease traffic; measure the reconvergence window (alive-set equals
  survivors + every subscription healed via the probe publish) and
  assert ZERO lost accepted leases and no survivor missing a death.
- **determinism**: the same seed replays a byte-identical chaos
  journal.
- **multitenant** (round 13): 3 competing jobs (batch pri 0 / train
  pri 5 / serve pri 10, each quota-capped) creating gangs against the
  same 100 nodes under seeded ``preempt_job`` storms + node kills —
  measures high-priority time-to-placement on a full cluster (the
  preemption path end to end) and asserts zero quota violations in
  every ``summarize_jobs`` sample plus a byte-identical journal
  across two runs.
- **serving** (round 16): Serve as a first-class tenant. Two tenant
  Serve apps (real controller FSM + capacity gangs, sim replicas —
  ``SimServeApp``) take O(10^6) seeded open-loop requests with diurnal
  spikes against a cluster ~98% full of training gangs: every spike
  scale-up preempts training capacity, a seeded slot-scoped
  ``preempt_job`` storm warns every chat replica mid-spike, and
  scale-down drains back through the preemption-warning machinery.
  Asserts zero lost accepted requests, zero quota violations, bounded
  p99 through the storms, every serve drain completing PRE-fire (no
  serve gang ever burns a fire), every preempted training gang
  resuming afterward, and a byte-identical journal across two runs.

Usage::

    RAY_TPU_SOAK_NODES=100 python benchmarks/soak_bench.py \
        --json-out BENCH_r16.json
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ray_tpu._private import fault_injection as fi  # noqa: E402


def _pct(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def _populate_objects(cluster, n_objects: int):
    """Give the GCS a realistically non-empty object-location table so
    the per-death owned-value sweep has real work (the O(objects) path
    the batch fix collapses from k sweeps to one)."""
    from ray_tpu._private.protocol import RpcClient

    client = RpcClient(cluster.gcs_addr, timeout=30.0)
    try:
        n_nodes = len(cluster.raylets)
        for i in range(n_objects):
            oid = b"soakobj-%08d" % i
            node = cluster.raylets[i % n_nodes].node_id
            client.call("add_object_location", object_id=oid,
                        node_id=node, size=1024)
    finally:
        client.close()


def fanout_phase(nodes: int, seed: int, coalesce: bool,
                 n_objects: int, verbose=print) -> dict:
    from ray_tpu._private.sim_cluster import SimCluster

    os.environ["RAY_TPU_GCS_DEATH_COALESCE_WINDOW_S"] = (
        "0.05" if coalesce else "0")
    fi.install(seed, "kill_node:*.mass_kill:p0.1")
    cluster = SimCluster(n_nodes=nodes, tick_interval=0.05,
                         poll_timeout=2.0).start()
    try:
        _populate_objects(cluster, n_objects)
        cluster.run_ticks(3, leases_every=2)
        cluster.mass_consult("mass_kill")
        t0 = cluster.metrics["mass_kill_initiated_at"]
        killed = cluster.dead_ids()
        cluster.run_ticks(4, leases_every=2)
        conv = cluster.wait_converged(timeout=45.0)
        lat = cluster.fanout_latencies(t0, killed)
        leases = cluster.verify_leases()
        st = cluster.gcs_call("debug_state")

        def _ms(v):
            # a p0.1 schedule can legitimately kill ZERO nodes at small
            # fleet sizes — report a degenerate phase, don't crash
            return round(v * 1e3, 2) if v is not None else None

        out = {
            "coalesce": coalesce,
            "killed": len(killed),
            "survivors": len(cluster.survivors()),
            "pairs_observed": len(lat),
            "pairs_expected": len(killed) * len(cluster.survivors()),
            "fanout_p50_ms": _ms(_pct(lat, 0.50)),
            "fanout_p99_ms": _ms(_pct(lat, 0.99)),
            "fanout_max_ms": _ms(max(lat) if lat else None),
            "reconvergence": conv,
            "lost_leases": len(leases["lost"]),
            "death_batches": st["death_batches"],
            "max_death_batch": st["max_death_batch"],
            "journal_sha256": hashlib.sha256(
                cluster.journal_text().encode()).hexdigest(),
        }
        verbose(f"  fanout[coalesce={coalesce}] killed={out['killed']} "
                f"p50={out['fanout_p50_ms']}ms "
                f"p99={out['fanout_p99_ms']}ms "
                f"converged={conv['converged']} "
                f"lost_leases={out['lost_leases']}")
        return out
    finally:
        cluster.stop()
        fi.uninstall()
        del os.environ["RAY_TPU_GCS_DEATH_COALESCE_WINDOW_S"]


def restart_phase(nodes: int, seed: int, verbose=print) -> dict:
    from ray_tpu._private.sim_cluster import SimCluster

    fi.install(seed, "kill_node:*.mass_kill:p0.1;"
                     "flap_node:*.flap_check:p0.05:400")
    store = os.path.join(tempfile.mkdtemp(prefix="soak_gcs_"), "gcs.db")
    cluster = SimCluster(n_nodes=nodes, tick_interval=0.05,
                         poll_timeout=2.0, gcs="subprocess",
                         store_path=store).start()
    try:
        cluster.run_ticks(3, leases_every=2)
        cluster.mass_consult("mass_kill")
        cluster.mass_consult("flap_check")
        killed = cluster.dead_ids()
        # the reconnect storm: SIGKILL the GCS mid-storm, bring it back
        # on the same port+store; every surviving client heals with
        # jittered arrival into the bounded admission gate
        t_restart = time.monotonic()
        cluster.restart_gcs(downtime_s=0.3)
        cluster.run_ticks(12, leases_every=3)   # flaps rejoin in here
        conv = cluster.wait_converged(timeout=60.0)
        reconv_s = time.monotonic() - t_restart
        leases = cluster.verify_leases()
        st = cluster.gcs_call("debug_state")
        missing_feeds = [
            r.tag for r in cluster.survivors()
            if not killed <= set(r.deaths_seen)]
        out = {
            "killed": len(killed),
            "flapped": sum(1 for line in cluster.journal
                           if "flap_node" in line and "down_ticks" in
                           line),
            "survivors": len(cluster.survivors()),
            "reconvergence_after_restart_s": round(reconv_s, 3),
            "converged": conv["converged"],
            "probe_healed": conv["probe_healed"],
            "accepted_leases": leases["accepted"],
            "lost_leases": len(leases["lost"]),
            "survivors_missing_deaths": missing_feeds,
            "pubsub_resyncs_served": st["pubsub_resyncs_served"],
            "register_throttled": st["register_throttled"],
            "journal_sha256": hashlib.sha256(
                cluster.journal_text().encode()).hexdigest(),
        }
        out["journal_text"] = cluster.journal_text()
        verbose(f"  restart: killed={out['killed']} "
                f"reconverged in {out['reconvergence_after_restart_s']}s "
                f"leases {out['accepted_leases']}/"
                f"lost {out['lost_leases']} "
                f"resyncs={out['pubsub_resyncs_served']} "
                f"throttled={out['register_throttled']}")
        return out
    finally:
        cluster.stop()
        fi.uninstall()


MT_SCHEDULE = ("preempt_job:train.job_tick:%3:300;"
               "preempt_job:batch.job_tick:%4:300;"
               "kill_node:*.mt_kill:p0.04")


def multitenant_phase(nodes: int, seed: int, verbose=print) -> dict:
    """Round-13 phase: competing quota-capped jobs + seeded preemption
    storms + node kills on one 100-node control plane."""
    from ray_tpu._private.sim_cluster import SimCluster

    os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"] = "0.3"
    fi.install(seed, MT_SCHEDULE)
    cluster = SimCluster(n_nodes=nodes, tick_interval=0.05,
                         poll_timeout=2.0).start()
    try:
        cpus = 4.0 * nodes
        # quotas sum past 100%: batch+train can SATURATE the cluster, so
        # every serve scale-up must go through the preemption path —
        # the latency this phase exists to measure
        cluster.register_job("batch", quota={"CPU": cpus * 0.6},
                             priority=0)
        cluster.register_job("train", quota={"CPU": cpus * 0.5},
                             priority=5)
        cluster.register_job("serve", quota={"CPU": cpus * 0.1},
                             priority=10)
        cluster.run_ticks(2)
        # fill the cluster: batch + train gangs up to (and past) quota —
        # the overflow gangs exercise the quota-block path
        for _ in range(int(cpus * 0.6 / 8) + 2):
            cluster.create_job_pg("batch", n_bundles=4, cpu=2.0)
        for _ in range(int(cpus * 0.5 / 8) + 2):
            cluster.create_job_pg("train", n_bundles=4, cpu=2.0)
        cluster.run_ticks(4)
        cluster.sample_jobs()
        # seeded preemption storm + composed node kills, with serve
        # scale-ups arriving against a full cluster
        placement_waits = []
        for round_n in range(6):
            cluster.jobs_tick()
            if round_n == 2:
                cluster.mass_consult("mt_kill")
            if round_n % 2 == 0:
                pg_id = cluster.create_job_pg("serve", n_bundles=2,
                                              cpu=1.0)
                t0 = time.monotonic()
                deadline = t0 + 20.0
                placed = False
                while time.monotonic() < deadline:
                    snap = cluster.gcs_call("get_placement_group",
                                            pg_id=pg_id)
                    if snap and snap["State"] == "CREATED":
                        placed = True
                        break
                    time.sleep(0.05)
                placement_waits.append(
                    {"placed": placed,
                     "wait_ms": round((time.monotonic() - t0) * 1e3, 1)})
            cluster.run_ticks(3)
            cluster.sample_jobs()
        conv = cluster.wait_converged(timeout=45.0)
        st = cluster.gcs_call("debug_state")
        samples = cluster.metrics.get("job_samples", [])
        waits = [w["wait_ms"] for w in placement_waits if w["placed"]]
        out = {
            "nodes": nodes,
            "killed": len(cluster.dead_ids()),
            "preemptions_fired": st.get("preemptions_fired", 0),
            "quota_rejections": st.get("quota_rejections", 0),
            "pending_pgs_end": st.get("pending_pgs", 0),
            "violations_total": sum(len(s["violations"])
                                    for s in samples),
            "samples": len(samples),
            "serve_placements": placement_waits,
            "serve_placement_p50_ms": _pct(waits, 0.50),
            "serve_placement_max_ms": _pct(waits, 1.0),
            "serve_placed_all": all(w["placed"]
                                    for w in placement_waits),
            "reconvergence": conv,
            "journal_sha256": hashlib.sha256(
                cluster.journal_text().encode()).hexdigest(),
            "journal_text": cluster.journal_text(),
        }
        verbose(f"  multitenant: killed={out['killed']} "
                f"preemptions={out['preemptions_fired']} "
                f"violations={out['violations_total']} "
                f"serve p50 wait={out['serve_placement_p50_ms']}ms "
                f"(all placed: {out['serve_placed_all']})")
        return out
    finally:
        cluster.stop()
        fi.uninstall()
        del os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"]


# one slot-scoped preemption storm rule: counters are per (slot-tag,
# method), so every chat slot's counter crosses 260 on the same tick —
# all four replicas warned SIMULTANEOUSLY, mid-spike (and again ~13s
# later at counter 520, post-spike)
SERVE_SCHEDULE = "preempt_job:svc-chat.serve_tick:%260:400"


def _wait_gangs_created(cluster, pg_ids, timeout_s: float) -> list:
    """Poll the gangs to CREATED, keeping the (journal-silent) gossip
    ticks flowing — pending placement is capacity-event driven."""
    deadline = time.monotonic() + timeout_s
    while True:
        states = [(cluster.gcs_call("get_placement_group", pg_id=p)
                   or {}).get("State") for p in pg_ids]
        if all(s == "CREATED" for s in states) \
                or time.monotonic() > deadline:
            return states
        cluster.run_ticks(2)


def serving_phase(nodes: int, seed: int, verbose=print) -> dict:
    """Round-16 phase: Serve as a first-class tenant under a
    million-request mixed workload on a training-saturated cluster."""
    from ray_tpu._private import events as _events
    from ray_tpu._private.sim_cluster import SimCluster

    os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"] = "0.5"
    fi.install(seed, SERVE_SCHEDULE)
    ev0 = _events.stats()["recorded"]
    cluster = SimCluster(n_nodes=nodes, tick_interval=0.05,
                         poll_timeout=2.0).start()
    try:
        cpus = 4.0 * nodes
        # training tenants fill ~98% of the cluster: serve baselines fit
        # in the slack, but every SPIKE scale-up must go through the
        # preemption path — and hand the capacity back afterward
        cluster.register_job("train-lo", quota={"CPU": cpus * 0.8},
                             priority=0)
        cluster.register_job("train-hi", quota={"CPU": cpus * 0.2},
                             priority=5)
        lo_gangs = [cluster.create_job_pg("train-lo", n_bundles=4,
                                          cpu=2.0)
                    for _ in range(int(cpus * 0.8 / 8))]
        hi_gangs = [cluster.create_job_pg("train-hi", n_bundles=4,
                                          cpu=2.0)
                    for _ in range(int(cpus * 0.18 / 8))]
        cluster.run_ticks(4)
        chat = cluster.add_serve_app(
            "chat", "svc-chat", priority=10, quota={"CPU": 8.0},
            base_rate=1200, service_rate=600, min_replicas=2,
            max_replicas=4, capacity_cpu=2.0,
            spikes=((200, 320, 3.0),))
        embed = cluster.add_serve_app(
            "embed", "svc-embed", priority=8, quota={"CPU": 6.0},
            base_rate=600, service_rate=600, min_replicas=1,
            max_replicas=3, capacity_cpu=2.0,
            spikes=((380, 470, 3.0),))
        # warm-up (min replicas place), then the soak proper: diurnal
        # spikes + the seeded mid-spike slot-preempt storm
        cluster.run_ticks(40)
        cluster.sample_jobs()
        for _ in range(6):
            cluster.run_ticks(100)
            cluster.sample_jobs()
        # end of load: drain the queues dry through the real
        # scale-down-by-warning path
        chat.base_rate = embed.base_rate = 0
        cluster.run_ticks(60)
        cluster.sample_jobs()
        chat_out, embed_out = chat.finalize(), embed.finalize()
        # freeze the serve plane before the resume-wait: it ticks a
        # wall-clock-dependent number of times, and app chaos consults
        # there would diverge the journal between same-seed runs
        cluster.serve_apps.clear()
        gangs = lo_gangs + hi_gangs
        states = _wait_gangs_created(cluster, gangs, timeout_s=30.0)
        resumed = sum(1 for s in states if s == "CREATED")
        st = cluster.gcs_call("debug_state")
        jobs = {r["Job"]: r for r in cluster.gcs_call("list_jobs")}
        serve_fires = sum(jobs[j].get("Preemptions", 0)
                          for j in ("svc-chat", "svc-embed") if j in jobs)
        samples = cluster.metrics.get("job_samples", [])
        evs = [e for e in _events.snapshot() if e["seq"] > ev0]
        waits = sorted(e["wait_s"] for e in evs
                       if e["kind"] == "SERVE_CAPACITY_PLACED")
        warned = [e for e in evs if e["kind"] == "SERVE_REPLICA_WARNED"]
        out = {
            "nodes": nodes,
            "ticks": cluster.tick_count,
            "apps": {"chat": chat_out, "embed": embed_out},
            "offered_total": chat_out["offered"] + embed_out["offered"],
            "lost_accepted_total": chat_out["lost"] + embed_out["lost"],
            "shed_total": chat_out["shed"] + embed_out["shed"],
            "violations_total": sum(len(s["violations"])
                                    for s in samples),
            "samples": len(samples),
            "preemptions_fired": st.get("preemptions_fired", 0),
            "serve_gang_fires": serve_fires,
            "warned_drains": len(warned),
            "warned_reasons": sorted({e.get("reason") for e in warned}),
            "capacity_wait_p50_ms": (
                round(_pct(waits, 0.50) * 1e3, 1) if waits else None),
            "capacity_wait_p99_ms": (
                round(_pct(waits, 0.99) * 1e3, 1) if waits else None),
            "capacity_gangs_placed": len(waits),
            "training_gangs": len(gangs),
            "training_resumed": resumed,
            "journal_sha256": hashlib.sha256(
                cluster.journal_text().encode()).hexdigest(),
            "journal_text": cluster.journal_text(),
        }
        verbose(f"  serving: offered={out['offered_total']} "
                f"lost={out['lost_accepted_total']} "
                f"shed={out['shed_total']} "
                f"chat p99={chat_out['latency_p99_s']}s "
                f"warned={out['warned_drains']} "
                f"serve_fires={serve_fires} "
                f"train resumed {resumed}/{len(gangs)}")
        return out
    finally:
        cluster.stop()
        fi.uninstall()
        del os.environ["RAY_TPU_GCS_PREEMPT_GRACE_S"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int,
                    default=int(os.environ.get("RAY_TPU_SOAK_NODES",
                                               "100")))
    ap.add_argument("--seed", type=int, default=12)
    ap.add_argument("--objects", type=int, default=20000,
                    help="object-location rows populating the GCS sweep")
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args()

    print(f"soak bench: {args.nodes} simulated raylets, seed {args.seed}")
    t0 = time.time()
    print("phase 1/8: death-feed fanout, coalescing OFF (pre-fix path)")
    before = fanout_phase(args.nodes, args.seed, coalesce=False,
                          n_objects=args.objects)
    print("phase 2/8: death-feed fanout, coalescing ON")
    after = fanout_phase(args.nodes, args.seed, coalesce=True,
                         n_objects=args.objects)
    print("phase 3/8: GCS restart mid-storm (reconnect herd)")
    restart = restart_phase(args.nodes, args.seed)
    print("phase 4/8: determinism replay (same seed, same journal)")
    replay = restart_phase(args.nodes, args.seed,
                           verbose=lambda *_a, **_k: None)
    journals_equal = (replay["journal_text"] == restart["journal_text"])
    restart.pop("journal_text", None)
    replay.pop("journal_text", None)
    print("phase 5/8: multi-tenant (3 jobs, seeded preemptions + kills)")
    mt = multitenant_phase(args.nodes, args.seed)
    print("phase 6/8: multi-tenant determinism replay")
    mt_replay = multitenant_phase(args.nodes, args.seed,
                                  verbose=lambda *_a, **_k: None)
    mt_journals_equal = (mt_replay["journal_text"] == mt["journal_text"])
    mt.pop("journal_text", None)
    mt_replay.pop("journal_text", None)
    print("phase 7/8: serving soak (2 tenant Serve apps + 2 training "
          "jobs, million-request mixed workload)")
    serving = serving_phase(args.nodes, args.seed)
    print("phase 8/8: serving determinism replay")
    serving_replay = serving_phase(args.nodes, args.seed,
                                   verbose=lambda *_a, **_k: None)
    serving_equal = (serving_replay["journal_text"]
                     == serving["journal_text"])
    serving.pop("journal_text", None)
    serving_replay.pop("journal_text", None)

    result = {
        "round": 16,
        "bench": "cluster_soak",
        "nodes": args.nodes,
        "seed": args.seed,
        "objects": args.objects,
        "schedule_fanout": "kill_node:*.mass_kill:p0.1",
        "schedule_restart": ("kill_node:*.mass_kill:p0.1;"
                             "flap_node:*.flap_check:p0.05:400"),
        "fanout_before": before,
        "fanout_after": after,
        "fanout_p99_improvement_x": (
            round(before["fanout_p99_ms"] / after["fanout_p99_ms"], 2)
            if before["fanout_p99_ms"] and after["fanout_p99_ms"]
            else None),
        "restart": restart,
        "schedule_multitenant": MT_SCHEDULE,
        "multitenant": mt,
        "schedule_serving": SERVE_SCHEDULE,
        "serving": serving,
        "determinism": {
            "journals_equal": journals_equal,
            "journal_sha256": restart["journal_sha256"],
            "multitenant_journals_equal": mt_journals_equal,
            "multitenant_journal_sha256": mt["journal_sha256"],
            "serving_journals_equal": serving_equal,
            "serving_journal_sha256": serving["journal_sha256"],
        },
        "acceptance": {
            "serving_million_requests":
                serving["offered_total"] >= 1_000_000,
            "serving_zero_lost_accepted":
                serving["lost_accepted_total"] == 0,
            "serving_zero_quota_violations":
                serving["violations_total"] == 0,
            "serving_p99_bounded": all(
                a["latency_p99_s"] is not None
                and a["latency_p99_s"] <= 3.0
                for a in serving["apps"].values()),
            "serving_storm_observed": (
                serving["warned_drains"] > 0
                and serving["preemptions_fired"] > 0),
            "serving_drains_pre_fire": serving["serve_gang_fires"] == 0,
            "serving_training_resumed": (
                serving["training_resumed"]
                == serving["training_gangs"]),
            "serving_reproducible": serving_equal,
            "zero_quota_violations": mt["violations_total"] == 0,
            "preemptions_fired": mt["preemptions_fired"] > 0,
            "high_pri_always_placed": mt["serve_placed_all"],
            "multitenant_reproducible": mt_journals_equal,
            "zero_lost_leases": (before["lost_leases"] == 0
                                 and after["lost_leases"] == 0
                                 and restart["lost_leases"] == 0),
            "all_subscriptions_healed": (
                restart["probe_healed"]
                and not restart["survivors_missing_deaths"]),
            "reconverged_bounded": restart["converged"],
            "reproducible": journals_equal,
            "fanout_p99_improved": (
                before["fanout_p99_ms"] is not None
                and after["fanout_p99_ms"] is not None
                and before["fanout_p99_ms"] > after["fanout_p99_ms"]),
        },
        "wall_s": round(time.time() - t0, 1),
    }
    print(json.dumps(result["acceptance"], indent=2))
    print(f"fanout p99: {before['fanout_p99_ms']}ms -> "
          f"{after['fanout_p99_ms']}ms "
          f"({result['fanout_p99_improvement_x']}x); "
          f"reconvergence after restart: "
          f"{restart['reconvergence_after_restart_s']}s; "
          f"multitenant: {mt['preemptions_fired']} preemptions, "
          f"{mt['violations_total']} violations, serve placement p50 "
          f"{mt['serve_placement_p50_ms']}ms")
    print(f"serving: {serving['offered_total']} requests, "
          f"{serving['lost_accepted_total']} lost, "
          f"{serving['warned_drains']} warned drains "
          f"({serving['serve_gang_fires']} serve fires), "
          f"capacity wait p50 {serving['capacity_wait_p50_ms']}ms, "
          f"training resumed {serving['training_resumed']}/"
          f"{serving['training_gangs']}")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
        print(f"wrote {args.json_out}")
    return 0 if all(result["acceptance"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
