"""Multi-slice MPMD pipeline bench (round 14).

Phases:

1. **bubble sweep** — a 2-stage SleepStage pipeline (contention-immune
   per-microbatch compute) swept over the microbatch count: measured
   per-step bubble fraction (schedule stalls stamped by the train loop)
   against the (P-1)/(M+P-1) theoretical curve, plus step wall clock.
   This is the acceptance artifact: the schedule's bubble obeys theory,
   and adding microbatches buys the predicted efficiency.
2. **wire** — a 2-stage DenseStage pipeline with a wide activation
   (microbatch x 4096 float32) run with the inter-stage hop exact vs
   bf16 (`PipelineConfig.wire_dtype`), reporting step walls and the
   LIVE `ray_tpu_collective_wire_bytes_total` compression ratio
   (sender-side accounting; polled while the gang runs).

Runs on an in-process simulated 2-slice cluster (one host per slice,
fake topology injected through the raylet's `tpu_topology` hook), so
the SPREAD_ACROSS_SLICES scheduler and the whole stage-per-slice data
path are exercised for real — only the ICI itself is simulated.

Usage: python benchmarks/pipeline_bench.py [--json-out BENCH_r14.json]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import threading
import time


def _start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=False)
    cluster.head_node = cluster.add_node(num_cpus=4)
    for sid in ("s0", "s1"):
        cluster.add_node(num_cpus=4, num_tpus=4,
                         tpu_topology={"slice_id": sid, "worker_id": 0,
                                       "chips": 4})
    cluster.connect()
    return cluster


def bubble_sweep(microbatch_counts, steps: int, fwd_s: float) -> list[dict]:
    from ray_tpu.train.pipeline import (PipelineConfig, PipelineTrainer,
                                        SleepStage,
                                        theoretical_bubble_fraction)

    P = 2
    rows = []
    for m in microbatch_counts:
        stages = [SleepStage(4, fwd_s=fwd_s) for _ in range(P)]
        result = PipelineTrainer(
            stages,
            pipeline_config=PipelineConfig(num_microbatches=m,
                                           group_name=f"bench_bub_m{m}"),
            num_steps=steps, microbatch_size=2, learning_rate=0.0,
            seed=1).fit()
        assert result.error is None, result.error
        hist = result.metrics_history[1:]   # drop the warmup step
        fracs = [r["bubble_fraction"] for r in hist]
        walls = [r["step_wall_s"] for r in hist]
        theory = theoretical_bubble_fraction(P, m)
        rows.append({
            "microbatches": m,
            "bubble_theory": round(theory, 4),
            "bubble_measured_mean": round(statistics.mean(fracs), 4),
            "bubble_measured_p50": round(statistics.median(fracs), 4),
            "step_wall_p50_s": round(statistics.median(walls), 4),
            # ideal wall = 2 * (M + P - 1) * fwd_s (fwd+bwd slots)
            "step_wall_ideal_s": round(2 * (m + P - 1) * fwd_s, 4),
            "abs_err": round(abs(statistics.mean(fracs) - theory), 4),
        })
        print(f"  M={m:>2}  theory={theory:.3f}  "
              f"measured={rows[-1]['bubble_measured_mean']:.3f}  "
              f"wall_p50={rows[-1]['step_wall_p50_s']:.3f}s")
    return rows


def wire_phase(steps: int, dim: int, mb_size: int) -> dict:
    import numpy as np   # noqa: F401  (DenseStage pulls it anyway)

    from ray_tpu.train.pipeline import (DenseStage, PipelineConfig,
                                        PipelineTrainer)

    M = 4
    out: dict = {"activation_elems": mb_size * dim, "microbatches": M}
    for fmt in ("off", "bf16"):
        group = f"bench_wire_{fmt}"
        wire_rows: list = []
        stop = threading.Event()

        def _poll(group=group, wire_rows=wire_rows, stop=stop):
            from ray_tpu.experimental.state.api import metrics_summary

            while not stop.is_set():
                try:
                    snaps = {m["name"]: m for m in metrics_summary()}
                    wb = snaps.get("ray_tpu_collective_wire_bytes_total")
                    rows = [v for v in (wb or {}).get("values", ())
                            if v["tags"].get("group") == group
                            and v["tags"].get("op") == "send"]
                    if rows:
                        wire_rows[:] = [dict(v) for v in rows]
                except Exception:
                    pass
                time.sleep(0.15)

        poller = threading.Thread(target=_poll, daemon=True)
        poller.start()
        stages = [DenseStage(dim, dim, "none"), DenseStage(dim, 3, "none")]
        t0 = time.monotonic()
        result = PipelineTrainer(
            stages,
            pipeline_config=PipelineConfig(
                num_microbatches=M,
                wire_dtype=None if fmt == "off" else fmt,
                group_name=group),
            num_steps=steps, microbatch_size=mb_size,
            learning_rate=0.01, seed=2).fit()
        stop.set()
        poller.join(timeout=5)
        assert result.error is None, result.error
        walls = [r["step_wall_s"] for r in result.metrics_history[1:]]
        by_fmt: dict = {}
        for v in wire_rows:
            by_fmt[v["tags"].get("format")] = \
                by_fmt.get(v["tags"].get("format"), 0.0) + v["value"]
        out[fmt] = {"step_wall_p50_s": round(statistics.median(walls), 4),
                    "wire_bytes_by_format": by_fmt,
                    "final_loss": result.metrics["loss"]}
        print(f"  wire={fmt}: wall_p50="
              f"{out[fmt]['step_wall_p50_s']}s bytes={by_fmt}")
    bf16_b = out["bf16"]["wire_bytes_by_format"].get("bf16", 0.0)
    # exact sends don't account wire bytes, so the honest denominator is
    # the ANALYTIC activation payload of the hops the bf16 run
    # quantized: steps x M microbatches x (mb x dim) float32 (grads stay
    # exact in both runs and aren't counted on either side)
    payload = float(steps * M * mb_size * dim * 4)
    out["bf16_activation_bytes"] = bf16_b
    out["exact_activation_payload_bytes"] = payload
    out["compression_vs_payload"] = round(payload / bf16_b, 3) \
        if bf16_b else None
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json-out", default=None)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--fwd-s", type=float, default=0.03)
    ap.add_argument("--microbatches", default="1,2,4,8,16")
    ap.add_argument("--wire-dim", type=int, default=4096)
    args = ap.parse_args(argv)
    os.environ.setdefault("RAY_TPU_TESTING", "1")

    cluster = _start_cluster()
    try:
        print("== phase 1: bubble sweep (P=2, SleepStage) ==")
        ms = [int(x) for x in str(args.microbatches).split(",") if x]
        sweep = bubble_sweep(ms, args.steps, args.fwd_s)
        print("== phase 2: inter-stage wire (DenseStage, bf16 vs off) ==")
        wire = wire_phase(args.steps, args.wire_dim, mb_size=8)
        worst = max(r["abs_err"] for r in sweep)
        report = {
            "bench": "pipeline_mpmd",
            "round": 14,
            "host": os.uname().nodename,
            "when_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "num_stages": 2,
            "bubble_sweep": sweep,
            "wire": wire,
            "acceptance": {
                "bubble_within_tolerance": bool(worst <= 0.1),
                "bubble_worst_abs_err": worst,
                "bf16_wire_bytes_recorded":
                    bool(wire["bf16_activation_bytes"] > 0),
            },
        }
        out = json.dumps(report, indent=1, sort_keys=True)
        print(out)
        if args.json_out:
            with open(args.json_out, "w") as f:
                f.write(out + "\n")
            print(f"wrote {args.json_out}")
        return 0
    finally:
        try:
            import ray_tpu

            ray_tpu.shutdown()
            cluster.shutdown()
        except Exception:
            pass


if __name__ == "__main__":
    raise SystemExit(main())
