"""Memory-anatomy overhead benchmark — PERF.md round 18 artifact.

Three phases, one JSON artifact (BENCH_r18.json):

1. **hook hot path** — the absolute cost the provenance ledger adds to
   one store cycle (put + pinned get + delete = note_put / note_pin /
   note_unpin / note_delete). Measured the way the tier-1 guard does
   (`tests/test_zz_memory_anatomy.py::test_overhead_guard_store_put_get_under_5pct`):
   a 4 MB cycle is bandwidth-bound with tens-of-µs round noise, so an
   on-vs-off wall-clock A/B over the big op can never resolve a µs-scale
   hook. Instead the hook cost is resolved on a tiny (64 B) cycle where
   the op itself is ~20 µs — alternating telemetry on/off rounds, min of
   round medians — and then expressed against the REAL op cost, a 4 MB
   put + to_bytes + delete cycle timed with telemetry off.
2. **leak sweep scaling** — wall time of one `Ledger.sweep` reconcile
   pass (store listing join + referenced/orphan classification) at 1k
   and 10k live ledger records, per-object µs. This is the periodic
   background cost knob `RAY_TPU_MEMORY_SWEEP_INTERVAL_S` amortizes.
3. **snapshot cost** — one `Ledger.snapshot()` (gauge flush + category
   rollup + ring materialization) at the same record counts; this is
   what a `summarize_memory()` fan-out or `/api/memory` scrape pays
   per process.

Usage:
  python benchmarks/memory_bench.py --json-out BENCH_r18.json
  python benchmarks/memory_bench.py --tiny-n 60 --rounds 5 --big-mb 4
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cycle(store, oid, payload, n):
    """Median seconds of n put+get(to_bytes)+delete cycles."""
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        store.put(oid, payload)
        pin = store.get(oid)
        pin.to_bytes()
        pin.release()
        store.delete(oid)
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def bench_hook_hot_path(store, *, tiny_n, rounds, big_mb, big_n):
    from ray_tpu._private import telemetry as _tm

    tiny = b"x" * 64
    big = os.urandom(big_mb * 1024 * 1024)
    oid = b"membench________"
    saved = _tm.ENABLED
    try:
        # warm both arms (ledger/Record import + store slot reuse) so
        # round 1 doesn't charge one-time costs to the hooks
        _tm.ENABLED = True
        _cycle(store, oid, tiny, 10)
        _tm.ENABLED = False
        _cycle(store, oid, tiny, 10)
        # alternate off/on rounds so drift hits both arms equally
        off, on = [], []
        for _ in range(rounds):
            _tm.ENABLED = False
            off.append(_cycle(store, oid, tiny, tiny_n))
            _tm.ENABLED = True
            on.append(_cycle(store, oid, tiny, tiny_n))
        _tm.ENABLED = False
        op_cost = min(_cycle(store, oid, big, big_n) for _ in range(3))
    finally:
        _tm.ENABLED = saved
    hook_cost = max(0.0, min(on) - min(off))
    return {
        "tiny_cycle_off_us": round(min(off) * 1e6, 3),
        "tiny_cycle_on_us": round(min(on) * 1e6, 3),
        "hook_cost_per_cycle_us": round(hook_cost * 1e6, 3),
        "big_op_mb": big_mb,
        "big_op_cost_us": round(op_cost * 1e6, 1),
        "overhead_pct_of_big_op": round(100.0 * hook_cost / op_cost, 3),
    }


def _populated_ledger(store, n_records):
    """A fresh Ledger with n_records live entries whose oids all exist
    in the store listing (the sweep's join path, no pruning)."""
    from ray_tpu._private import memory_anatomy as ma

    led = ma.Ledger(ring_size=256)
    listed = {}
    for i in range(n_records):
        oid = b"swp" + i.to_bytes(4, "big") + b"\x00" * 9
        with ma.tagged("collective_segment", group="bench", epoch=1,
                       rank=i % 8):
            led.note_put(oid, 1024, pid=os.getpid())
        listed[oid] = 1024
    store.objs = listed          # duck-typed list_objects source
    return led


class _ListedStore:
    """list_objects()-only store shim so sweep scaling isolates the
    ledger's classification cost from shm syscalls."""

    def __init__(self):
        self.objs = {}

    def list_objects(self, max_objects: int = 65536):
        return list(self.objs.items())


def bench_sweep_and_snapshot(n_records):
    store = _ListedStore()
    led = _populated_ledger(store, n_records)
    # warm sweep/snapshot code paths (events + config imports) on a
    # throwaway ledger so the timed pass measures steady state
    warm_store = _ListedStore()
    warm = _populated_ledger(warm_store, 8)
    warm.sweep(warm_store, known_groups={"bench": 1}, poisoned={},
               grace_s=3600.0)
    warm.snapshot()
    t0 = time.perf_counter()
    orphans = led.sweep(store, known_groups={"bench": 1}, poisoned={},
                        grace_s=3600.0)
    sweep_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    snap = led.snapshot()
    snap_s = time.perf_counter() - t0
    return {
        "records": n_records,
        "orphans": len(orphans),
        "live_objects": snap["live_objects"],
        "sweep_ms": round(sweep_s * 1e3, 3),
        "sweep_us_per_object": round(sweep_s * 1e6 / n_records, 3),
        "snapshot_ms": round(snap_s * 1e3, 3),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny-n", type=int, default=60)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--big-mb", type=int, default=4)
    ap.add_argument("--big-n", type=int, default=25)
    ap.add_argument("--sweep-sizes", type=int, nargs="+",
                    default=[1000, 10000])
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args(argv)

    from ray_tpu._private.store_client import StoreClient

    store = StoreClient(f"membench_{os.getpid()}", create=True,
                        size=128 * 1024 * 1024, n_slots=256)
    try:
        hot = bench_hook_hot_path(store, tiny_n=args.tiny_n,
                                  rounds=args.rounds, big_mb=args.big_mb,
                                  big_n=args.big_n)
    finally:
        store.close()
    print(json.dumps({"phase": "hook_hot_path", **hot}), flush=True)

    sweeps = []
    for n in args.sweep_sizes:
        row = bench_sweep_and_snapshot(n)
        sweeps.append(row)
        print(json.dumps({"phase": "sweep", **row}), flush=True)

    record = {
        "bench": "memory_anatomy",
        "hook_hot_path": hot,
        "sweep": sweeps,
        "acceptance": {
            "overhead_under_5pct": hot["overhead_pct_of_big_op"] < 5.0,
            "sweep_subsecond_at_10k": all(
                r["sweep_ms"] < 1000.0 for r in sweeps),
        },
    }
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        print(f"wrote {args.json_out}", flush=True)
    return 0 if all(record["acceptance"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
