"""Scalability envelope harness — the release/benchmarks port.

Reference: /root/reference/release/benchmarks/ (many_nodes / many_actors /
many_tasks / many_pgs + object-store limits, the "Ray Scalability
Envelope" of BASELINE.md). Dimensions are scaled to the current machine
via --scale (1.0 = the smoke settings CI can afford on one small host;
raise it on a real cluster).

Run: python benchmarks/scalability_envelope.py [--scale 1.0]
Prints one JSON line per dimension plus a summary table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# many_actors spawns every worker process at once; on a small host the
# spawns serialize on the CPU, so give registration a generous budget
os.environ.setdefault("RAY_TPU_WORKER_REGISTER_TIMEOUT_S", "600")
# A wedged axon tunnel makes EVERY worker-process startup pay a slow
# plugin registration (~2.2s vs ~0.3s healthy), so a 400-actor storm can
# legitimately take ~15 min on the 1-core box — don't fail creations that
# are queued behind a draining spawn queue.
os.environ.setdefault("RAY_TPU_ACTOR_CREATION_RPC_TIMEOUT_S", "1200")


def bench(name, fn):
    t0 = time.perf_counter()
    extra = fn() or {}
    dt = time.perf_counter() - t0
    row = {"dimension": name, "seconds": round(dt, 2), **extra}
    print(json.dumps(row))
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    args = ap.parse_args()
    s = args.scale

    import ray_tpu

    store_bytes = 512 * 1024 * 1024
    ray_tpu.init(num_cpus=8, object_store_memory=store_bytes)
    rows = []

    # --- many queued tasks on one node (ref: 1M+ queued) -----------------
    n_tasks = int(2000 * s)

    @ray_tpu.remote(num_cpus=0, max_retries=0)
    def noop(i):
        return i

    def many_tasks():
        refs = [noop.remote(i) for i in range(n_tasks)]
        out = ray_tpu.get(refs, timeout=600)
        assert out == list(range(n_tasks))
        return {"tasks": n_tasks}

    rows.append(bench("many_queued_tasks", many_tasks))

    # --- many actors (ref: 10k+; each actor is a real OS process, so the
    # smoke default is sized for a small host — raise --scale on real
    # machines where process spawn isn't serialized on one core) ----------
    n_actors = int(40 * s)

    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return 1

    def many_actors():
        actors = [A.remote() for _ in range(n_actors)]
        # wedged-tunnel worker spawn costs ~2.2s/process serialized on
        # one core (PERF.md round 5f): the tail ping legitimately waits
        # out most of the storm — time it honestly, don't fail it
        out = ray_tpu.get([a.ping.remote() for a in actors], timeout=1800)
        assert sum(out) == n_actors
        for a in actors:
            ray_tpu.kill(a)
        return {"actors": n_actors}

    rows.append(bench("many_actors", many_actors))

    # --- warm actor spawn latency (verdict target: < 300 ms) -------------
    def warm_spawn():
        time.sleep(3.0)   # let the raylet's idle-pool refill settle
        t0 = time.perf_counter()
        a = A.remote()
        ray_tpu.get(a.ping.remote(), timeout=60)
        warm_ms = (time.perf_counter() - t0) * 1000
        ray_tpu.kill(a)
        return {"warm_spawn_ms": round(warm_ms, 1)}

    rows.append(bench("warm_actor_spawn", warm_spawn))

    # --- many placement groups (ref: 1k+) --------------------------------
    n_pgs = int(100 * s)

    def many_pgs():
        from ray_tpu.util.placement_group import (
            placement_group,
            remove_placement_group,
        )

        # size bundles so the WHOLE set fits node capacity — PGs beyond
        # capacity correctly stay PENDING forever, which measures the
        # wait-timeout, not PG throughput (hit at scale 10: 1000 x 0.01
        # CPU > the node's 8)
        cpu_per_pg = round(min(0.01, 8 * 0.8 / n_pgs), 4)
        pgs = [placement_group([{"CPU": cpu_per_pg}], strategy="PACK")
               for _ in range(n_pgs)]
        ready = sum(1 for pg in pgs if pg.wait(60))
        assert ready == n_pgs, f"{ready}/{n_pgs} PGs became ready"
        for pg in pgs:
            remove_placement_group(pg)
        return {"placement_groups": n_pgs}

    rows.append(bench("many_placement_groups", many_pgs))

    # --- object args to one task (ref: 10k+) ------------------------------
    n_args = int(1000 * s)

    @ray_tpu.remote(num_cpus=0, max_retries=0)
    def fan_in(*xs):
        return len(xs)

    def many_args():
        refs = [ray_tpu.put(i) for i in range(n_args)]
        assert ray_tpu.get(fan_in.remote(*refs), timeout=600) == n_args
        return {"object_args": n_args}

    rows.append(bench("many_object_args", many_args))

    # --- returns from one task (ref: 3k+) ---------------------------------
    n_returns = int(500 * s)

    def many_returns():
        @ray_tpu.remote(num_cpus=0, num_returns=n_returns, max_retries=0)
        def fan_out():
            return tuple(range(n_returns))

        refs = fan_out.remote()
        out = ray_tpu.get(refs, timeout=600)
        assert out == list(range(n_returns))
        return {"returns": n_returns}

    rows.append(bench("many_task_returns", many_returns))

    # --- large object get (ref: 100 GiB+; scaled to the store) ------------
    nbytes = int(128 * 1024 * 1024 * s)

    def big_get():
        # sized to FIT the shm store: this measures the data plane
        # (serialize → shm → pinned zero-copy-ish get), not the disk
        shm_bytes = min(nbytes, store_bytes // 2)
        arr = np.zeros(shm_bytes, dtype=np.uint8)
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref, timeout=600)
        assert out.nbytes == shm_bytes
        return {"gigabytes": round(shm_bytes / 2**30, 3)}

    rows.append(bench("large_object_get", big_get))

    def big_get_spilled():
        # deliberately larger than the store: measures the spill path,
        # whose floor is the DISK write rate, not the framework
        arr = np.zeros(nbytes, dtype=np.uint8)
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref, timeout=600)
        assert out.nbytes == nbytes
        return {"gigabytes": round(nbytes / 2**30, 3), "path": "spill"}

    if nbytes > store_bytes:
        rows.append(bench("large_object_get_spilled", big_get_spilled))

    print(json.dumps({"benchmark": "scalability_envelope", "scale": s,
                      "results": rows}))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
