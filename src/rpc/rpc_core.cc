// Native control-plane RPC core: framing, connection management, reply
// correlation and the request queue in C++; pickle and policy stay in
// Python (ray_tpu/_private/protocol.py).
//
// Reference role: src/ray/rpc/ (GrpcServer / ClientCallManager) — the
// reference runs its task submit/push hot path through compiled gRPC
// services with a thin Cython shim (python/ray/_raylet.pyx:1413); this
// plays the same part for the pickle-frame protocol. The Python
// fallback implementation remains authoritative for semantics; wire
// format is shared:
//
//   [len: u64 BE] [ver<<4 | kind: u8] [seq: i64 BE] [payload: len-9 bytes]
//
// kind (low nibble): 0 REQUEST, 1 REPLY, 2 PUSH. The high nibble is the
// protocol version (kProtocolVersion); a receiver that sees any other
// version prints a loud diagnostic and drops the connection instead of
// misparsing the stream. The payload is an opaque pickle — this layer
// never inspects it, exactly like gRPC treating message bodies as bytes.
//
// Threading model:
//   client: one reader thread per connection. Sync callers register
//     their seq before send and block on a condvar in rpc_cl_wait (GIL
//     released under ctypes); unclaimed replies and pushes go to an
//     async queue drained by one Python pump thread.
//   server: accept thread + one reader thread per connection feed a
//     single MPSC request queue; Python dispatcher(s) pop via
//     rpc_sv_next. Connect/disconnect are delivered in-band as
//     pseudo-frames (kind -2 / -1) so Python observes ordering.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// Bump when the frame layout or frame semantics change incompatibly.
// Must match PROTOCOL_VERSION in ray_tpu/_private/protocol.py.
// v3: PUSH_OOB frames (kind 3, out-of-band payload layout) — a v2
// receiver would misparse the head-prefixed body as pickle.
// v4: collective incarnation epochs (epoch slot in col frame keys and
// shm oid layout) — a v3 peer's frames never match a v4 mailbox key.
constexpr int kProtocolVersion = 4;

constexpr int kReq = 0;
constexpr int kReply = 1;
constexpr int kPush = 2;
// One-way out-of-band frame ([u32 head_len][pickle head][raw body]).
// This core routes the kind nibble opaquely — the constant exists so
// the cross-language wire-format lint (ray_tpu/_private/analysis/
// wire_format.py) can assert both sides agree on the value; keep in
// sync with PUSH_OOB in ray_tpu/_private/protocol.py.
constexpr int kPushOob = 3;
// self-check: the opaque pass-through below must still cover every kind
static_assert(kPushOob <= 0x0F, "frame kind must fit the low nibble");
constexpr int kEvDisconnect = -1;
constexpr int kEvConnect = -2;

// Timed condvar wait that ThreadSanitizer can SEE. libstdc++-10's
// condition_variable::wait_for rides pthread_cond_clockwait (glibc
// 2.30+), which this toolchain's libtsan does not intercept — tsan
// then misses the mutex release/reacquire inside the wait and reports
// phantom "double lock of a mutex" + data races between two threads
// that BOTH hold the lock (scripts/sanitize.sh reproduced this with a
// 25-line textbook producer/consumer). Under tsan, wait against
// system_clock instead: that path uses pthread_cond_timedwait, which
// IS intercepted. Production builds keep steady_clock (immune to
// wall-clock jumps); these are bounded re-checked poll waits either
// way.
template <typename Pred>
bool timed_wait(std::condition_variable& cv, std::unique_lock<std::mutex>& g,
                int timeout_ms, Pred ready) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(
      g, std::chrono::system_clock::now() + std::chrono::milliseconds(timeout_ms),
      ready);
#else
  return cv.wait_for(g, std::chrono::milliseconds(timeout_ms), ready);
#endif
}

struct Frame {
  uint64_t conn_id = 0;
  int kind = 0;
  int64_t seq = 0;
  char* buf = nullptr;   // malloc'd; ownership passes to the consumer
  size_t len = 0;
};

uint64_t be64(const unsigned char* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

void put_be64(unsigned char* p, uint64_t v) {
  for (int i = 7; i >= 0; i--) { p[i] = v & 0xff; v >>= 8; }
}

bool recv_exact(int fd, void* out, size_t n) {
  char* p = static_cast<char*>(out);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_all(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// One locked write: header + payload in a single buffer for small frames
// (avoids a partial-frame interleave and a second syscall); large
// payloads go as two sends under the same lock.
bool send_frame(int fd, std::mutex& wlock, int kind, int64_t seq,
                const char* buf, size_t len) {
  unsigned char hdr[17];
  put_be64(hdr, len + 9);
  hdr[8] = static_cast<unsigned char>((kProtocolVersion << 4) | (kind & 0x0F));
  put_be64(hdr + 9, static_cast<uint64_t>(seq));
  std::lock_guard<std::mutex> g(wlock);
  if (len <= 64 * 1024) {
    std::vector<char> one(sizeof(hdr) + len);
    memcpy(one.data(), hdr, sizeof(hdr));
    if (len) memcpy(one.data() + sizeof(hdr), buf, len);
    return send_all(fd, one.data(), one.size());
  }
  if (!send_all(fd, hdr, sizeof(hdr))) return false;
  return send_all(fd, buf, len);
}

// Reads one frame; on success fills kind/seq/buf/len (malloc'd buf).
// On a protocol-version mismatch sets *ver_mismatch (when given) so the
// caller can surface the NAMED error instead of a generic disconnect.
bool recv_frame(int fd, int* kind, int64_t* seq, char** buf, size_t* len,
                bool* ver_mismatch = nullptr) {
  unsigned char hdr[17];
  if (!recv_exact(fd, hdr, 8)) return false;
  uint64_t total = be64(hdr);
  if (total < 9 || total > (1ull << 40)) return false;
  if (!recv_exact(fd, hdr + 8, 9)) return false;
  int ver = hdr[8] >> 4;
  if (ver != kProtocolVersion) {
    fprintf(stderr,
            "ray-tpu rpc: protocol version mismatch (peer sent v%d, this "
            "build speaks v%d); closing connection\n",
            ver, kProtocolVersion);
    if (ver_mismatch) *ver_mismatch = true;
    return false;
  }
  *kind = static_cast<int>(hdr[8] & 0x0F);
  *seq = static_cast<int64_t>(be64(hdr + 9));
  *len = total - 9;
  *buf = static_cast<char*>(malloc(*len ? *len : 1));
  if (!*buf) return false;
  if (*len && !recv_exact(fd, *buf, *len)) {
    free(*buf);
    *buf = nullptr;
    return false;
  }
  return true;
}

// ------------------------------------------------------------------ client

struct Client {
  int fd = -1;
  std::mutex wlock;
  std::thread reader;
  std::mutex close_mu;   // serializes rpc_cl_close (double-join is UB)
  std::mutex mu;
  std::condition_variable cv;          // wakes sync waiters
  std::condition_variable async_cv;    // wakes the async pump
  std::unordered_set<int64_t> sync_waiting;
  std::unordered_map<int64_t, Frame> sync_done;
  std::deque<Frame> async_q;           // pushes + non-sync replies
  bool closed = false;
  bool ver_mismatch = false;   // closed because the peer speaks another rev

  void reader_loop() {
    bool vm = false;   // published under mu below (TSAN-clean)
    for (;;) {
      Frame f;
      if (!recv_frame(fd, &f.kind, &f.seq, &f.buf, &f.len, &vm)) break;
      std::lock_guard<std::mutex> g(mu);
      if (f.kind == kReply && sync_waiting.count(f.seq)) {
        sync_done[f.seq] = f;
        cv.notify_all();
      } else {
        async_q.push_back(f);
        async_cv.notify_one();
      }
    }
    // The mismatch path leaves a HEALTHY TCP connection behind; shut it
    // down so the peer sees the drop and no fd/conn leaks if the caller
    // never gets around to rpc_cl_close (shutdown — unlike close — is
    // safe against a concurrent rpc_cl_send on the same fd).
    ::shutdown(fd, SHUT_RDWR);
    std::lock_guard<std::mutex> g(mu);
    closed = true;
    ver_mismatch = vm;
    cv.notify_all();
    async_cv.notify_all();
  }
};

// ------------------------------------------------------------------ server

struct ServerConn {
  int fd = -1;
  std::mutex wlock;
  std::thread reader;
  bool alive = true;
  // The reader thread holds the last shared_ptr; closing the fd here —
  // and only here — means no close() can race a recv() on the same fd
  // (shutdown() is the wakeup mechanism, close is deferred to teardown).
  ~ServerConn() {
    if (fd >= 0) ::close(fd);
  }
};

struct Server {
  int lfd = -1;
  int port = 0;
  std::thread acceptor;
  std::mutex mu;                       // guards conns + queue
  std::condition_variable cv;
  std::deque<Frame> q;
  std::unordered_map<uint64_t, std::shared_ptr<ServerConn>> conns;
  uint64_t next_conn_id = 1;
  bool stopped = false;

  void push_event(uint64_t conn_id, int kind) {
    Frame f;
    f.conn_id = conn_id;
    f.kind = kind;
    f.buf = static_cast<char*>(malloc(1));
    f.len = 0;
    q.push_back(f);
    cv.notify_one();
  }

  void conn_loop(uint64_t conn_id, std::shared_ptr<ServerConn> c) {
    for (;;) {
      Frame f;
      if (!recv_frame(c->fd, &f.kind, &f.seq, &f.buf, &f.len)) break;
      f.conn_id = conn_id;
      std::lock_guard<std::mutex> g(mu);
      if (stopped) {
        free(f.buf);
        break;
      }
      q.push_back(f);
      cv.notify_one();
    }
    std::lock_guard<std::mutex> g(mu);
    c->alive = false;
    if (!stopped) push_event(conn_id, kEvDisconnect);
  }

  void accept_loop() {
    for (;;) {
      sockaddr_in peer{};
      socklen_t plen = sizeof(peer);
      int fd = ::accept(lfd, reinterpret_cast<sockaddr*>(&peer), &plen);
      if (fd < 0) {
        if (errno == EINTR) continue;
        return;   // listener closed
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(mu);
      if (stopped) {
        ::close(fd);
        return;
      }
      uint64_t id = next_conn_id++;
      auto c = std::make_shared<ServerConn>();
      c->fd = fd;
      conns[id] = c;
      push_event(id, kEvConnect);
      c->reader = std::thread([this, id, c] { conn_loop(id, c); });
      c->reader.detach();
    }
  }
};

}  // namespace

extern "C" {

void rpc_buf_free(char* buf) { free(buf); }

// ---------------------------------------------------------------- client C

void* rpc_cl_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return nullptr;
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    freeaddrinfo(res);
    return nullptr;
  }
  timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  if (::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    ::close(fd);
    freeaddrinfo(res);
    return nullptr;
  }
  freeaddrinfo(res);
  timeval zero{0, 0};
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &zero, sizeof(zero));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  c->reader = std::thread([c] { c->reader_loop(); });
  return c;
}

// expect_sync=1 registers seq for rpc_cl_wait BEFORE the frame leaves, so
// the reply can never race past an unregistered waiter.
int rpc_cl_send(void* h, int kind, long long seq, const char* buf,
                size_t len, int expect_sync) {
  auto* c = static_cast<Client*>(h);
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->closed) return 2;
    if (expect_sync) c->sync_waiting.insert(seq);
  }
  if (!send_frame(c->fd, c->wlock, kind, seq, buf, len)) {
    std::lock_guard<std::mutex> g(c->mu);
    c->sync_waiting.erase(seq);
    c->closed = true;
    c->cv.notify_all();
    c->async_cv.notify_all();
    return 2;
  }
  return 0;
}

// 0 = reply (out/out_len set), 1 = timeout (still waiting), 2 = closed.
int rpc_cl_wait(void* h, long long seq, int timeout_ms, char** out,
                size_t* out_len) {
  auto* c = static_cast<Client*>(h);
  std::unique_lock<std::mutex> g(c->mu);
  auto ready = [&] { return c->sync_done.count(seq) || c->closed; };
  if (timeout_ms < 0) {
    c->cv.wait(g, ready);
  } else if (!timed_wait(c->cv, g, timeout_ms, ready)) {
    return 1;
  }
  auto it = c->sync_done.find(seq);
  if (it == c->sync_done.end()) return 2;  // closed with no reply
  *out = it->second.buf;
  *out_len = it->second.len;
  c->sync_done.erase(it);
  c->sync_waiting.erase(seq);
  return 0;
}

// Abandon a sync wait (caller timed out at a higher level): the reply, if
// it still arrives, is rerouted to the async queue.
void rpc_cl_abandon(void* h, long long seq) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  c->sync_waiting.erase(seq);
  auto it = c->sync_done.find(seq);
  if (it != c->sync_done.end()) {
    c->async_q.push_back(it->second);
    c->sync_done.erase(it);
    c->async_cv.notify_one();
  }
}

// 0 = frame (kind/seq/out set), 1 = timeout, 2 = closed and drained.
int rpc_cl_poll_async(void* h, int timeout_ms, int* kind, long long* seq,
                      char** out, size_t* out_len) {
  auto* c = static_cast<Client*>(h);
  std::unique_lock<std::mutex> g(c->mu);
  auto ready = [&] { return !c->async_q.empty() || c->closed; };
  if (timeout_ms < 0) {
    c->async_cv.wait(g, ready);
  } else if (!timed_wait(c->async_cv, g, timeout_ms, ready)) {
    return 1;
  }
  if (c->async_q.empty()) return 2;
  Frame f = c->async_q.front();
  c->async_q.pop_front();
  *kind = f.kind;
  *seq = f.seq;
  *out = f.buf;
  *out_len = f.len;
  return 0;
}

int rpc_cl_closed(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return c->closed ? 1 : 0;
}

// 1 iff the connection died because the peer speaks a different protocol
// revision (lets Python raise ProtocolMismatch, not ConnectionLost).
int rpc_cl_ver_mismatch(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return c->ver_mismatch ? 1 : 0;
}

// Shut the connection down and reclaim its buffers. The Client struct
// itself intentionally leaks (a few hundred bytes): Python threads may
// still be inside rpc_cl_wait/rpc_cl_send when close races them, and a
// dangling handle there would be a use-after-free; the leaked struct
// just reports "closed" to them forever. Same policy as rpc_sv_stop.
void rpc_cl_close(void* h) {
  auto* c = static_cast<Client*>(h);
  std::lock_guard<std::mutex> close_g(c->close_mu);
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->closed && c->fd < 0) return;
    c->closed = true;
  }
  ::shutdown(c->fd, SHUT_RDWR);       // wakes the reader out of recv
  if (c->reader.joinable()) c->reader.join();
  std::lock_guard<std::mutex> g(c->mu);
  ::close(c->fd);
  c->fd = -1;
  for (auto& kv : c->sync_done) free(kv.second.buf);
  c->sync_done.clear();
  for (auto& f : c->async_q) free(f.buf);
  c->async_q.clear();
  c->cv.notify_all();
  c->async_cv.notify_all();
}

// ---------------------------------------------------------------- server C

void* rpc_sv_start(const char* host, int port) {
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return nullptr;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = host ? inet_addr(host) : htonl(INADDR_LOOPBACK);
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(lfd, 512) != 0) {
    ::close(lfd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new Server();
  s->lfd = lfd;
  s->port = ntohs(addr.sin_port);
  s->acceptor = std::thread([s] { s->accept_loop(); });
  return s;
}

int rpc_sv_port(void* h) { return static_cast<Server*>(h)->port; }

// 0 = frame, 1 = timeout, 2 = stopped and drained.
// kind -2/-1 are connect/disconnect events for conn_id (len 0).
int rpc_sv_next(void* h, int timeout_ms, unsigned long long* conn_id,
                int* kind, long long* seq, char** out, size_t* out_len) {
  auto* s = static_cast<Server*>(h);
  std::unique_lock<std::mutex> g(s->mu);
  auto ready = [&] { return !s->q.empty() || s->stopped; };
  if (timeout_ms < 0) {
    s->cv.wait(g, ready);
  } else if (!timed_wait(s->cv, g, timeout_ms, ready)) {
    return 1;
  }
  if (s->q.empty()) return 2;
  Frame f = s->q.front();
  s->q.pop_front();
  *conn_id = f.conn_id;
  *kind = f.kind;
  *seq = f.seq;
  *out = f.buf;
  *out_len = f.len;
  return 0;
}

int rpc_sv_send(void* h, unsigned long long conn_id, int kind,
                long long seq, const char* buf, size_t len) {
  auto* s = static_cast<Server*>(h);
  std::shared_ptr<ServerConn> c;
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->conns.find(conn_id);
    if (it == s->conns.end() || !it->second->alive) return 2;
    c = it->second;
  }
  if (!send_frame(c->fd, c->wlock, kind, seq, buf, len)) {
    c->alive = false;
    return 2;
  }
  return 0;
}

int rpc_sv_conn_alive(void* h, unsigned long long conn_id) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->conns.find(conn_id);
  return (it != s->conns.end() && it->second->alive) ? 1 : 0;
}

void rpc_sv_close_conn(void* h, unsigned long long conn_id) {
  auto* s = static_cast<Server*>(h);
  std::shared_ptr<ServerConn> c;
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->conns.find(conn_id);
    if (it == s->conns.end()) return;
    c = it->second;
    s->conns.erase(it);
  }
  c->alive = false;
  ::shutdown(c->fd, SHUT_RDWR);   // unblocks the reader; it closes the fd
}

void rpc_sv_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->stopped) return;
    s->stopped = true;
    s->cv.notify_all();
  }
  ::shutdown(s->lfd, SHUT_RDWR);
  if (s->acceptor.joinable()) s->acceptor.join();
  ::close(s->lfd);
  std::vector<std::shared_ptr<ServerConn>> cs;
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto& kv : s->conns) cs.push_back(kv.second);
    s->conns.clear();
  }
  for (auto& c : cs) {
    c->alive = false;
    ::shutdown(c->fd, SHUT_RDWR);   // readers close their own fds
  }
  // Readers hold shared_ptrs; frames they may still enqueue are dropped
  // by the stopped flag. Drain the queue.
  std::lock_guard<std::mutex> g(s->mu);
  for (auto& f : s->q) free(f.buf);
  s->q.clear();
  // NOTE: the Server object itself leaks by design — detached reader
  // threads may still touch mu briefly after stop; a few hundred bytes
  // per server per process is cheaper than a join protocol for threads
  // blocked in kernel recv. (Python creates a handful per process.)
}

}  // extern "C"
