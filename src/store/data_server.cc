// Native object data-plane server.
//
// Serves sealed objects from the shared-memory store over TCP with zero
// Python involvement on the serving side — the TPU-native analog of the
// reference's C++ ObjectManager chunk transfer
// (/root/reference/src/ray/object_manager/object_manager.h, chunked gRPC
// push/pull). The Python raylet keeps orchestrating WHICH objects move
// (locations, admission control); the bytes themselves are read out of
// the mmap'd segment and written to the socket by these threads, GIL-free.
//
// Wire protocol (all integers little-endian):
//   request : 16-byte object id | uint64 offset | uint64 max_length
//   response: uint64 total_size | uint64 payload_length | payload bytes
//             total_size == UINT64_MAX  => object not present (sealed) here
// Connections are persistent; one request/response at a time per
// connection (pullers pipeline by chunking sequentially, like the
// reference's per-chunk gRPC calls).

#include <cerrno>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <new>
#include <set>

struct Store;  // opaque; defined in store.cc (same translation library)

extern "C" {
int store_get(Store* s, const uint8_t* id, void** out_ptr,
              uint64_t* out_size);
int store_release(Store* s, const uint8_t* id);
}

namespace {

constexpr uint64_t kMissing = ~0ull;
constexpr size_t kReqSize = 32;  // 16B id + 8B offset + 8B length

// Server handle: tracks live connections so stop() can tear the whole
// thing down BEFORE the Store segment is destroyed (otherwise detached
// serving threads would touch unmapped memory — use-after-free).
struct DataServer {
  Store* store;
  int lfd;
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  std::set<int> conns;
  std::atomic<int> active{0};
  std::atomic<bool> stopping{false};
};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w < 0 && errno == EINTR) continue;
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

struct ConnArg {
  DataServer* srv;
  int fd;
};

void* conn_main(void* argp) {
  ConnArg* arg = static_cast<ConnArg*>(argp);
  DataServer* srv = arg->srv;
  Store* store = srv->store;
  int fd = arg->fd;
  delete arg;
  if (srv->stopping.load()) {
    close(fd);
    srv->active.fetch_sub(1);
    return nullptr;
  }
  pthread_mutex_lock(&srv->mu);
  srv->conns.insert(fd);
  pthread_mutex_unlock(&srv->mu);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Bounded sends: a hung/stopped puller must not pin the object (the
  // store_get refcount) forever — after the timeout the write fails, the
  // pin is released and the thread exits.
  timeval send_timeout{120, 0};
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &send_timeout,
             sizeof(send_timeout));
  uint8_t req[kReqSize];
  while (read_full(fd, req, kReqSize)) {
    if (srv->stopping.load()) break;
    uint64_t offset, max_len;
    memcpy(&offset, req + 16, 8);
    memcpy(&max_len, req + 24, 8);
    void* ptr = nullptr;
    uint64_t size = 0;
    uint64_t hdr[2];
    if (store_get(store, req, &ptr, &size) != 0) {
      hdr[0] = kMissing;
      hdr[1] = 0;
      if (!write_full(fd, hdr, sizeof(hdr))) break;
      continue;
    }
    uint64_t n = 0;
    if (offset < size) {
      n = size - offset;
      if (n > max_len) n = max_len;
    }
    hdr[0] = size;
    hdr[1] = n;
    bool ok = write_full(fd, hdr, sizeof(hdr)) &&
              (n == 0 ||
               write_full(fd, static_cast<uint8_t*>(ptr) + offset, n));
    store_release(store, req);
    if (!ok) break;
  }
  pthread_mutex_lock(&srv->mu);
  srv->conns.erase(fd);
  pthread_mutex_unlock(&srv->mu);
  close(fd);
  srv->active.fetch_sub(1);
  return nullptr;
}

void* accept_main(void* argp) {
  DataServer* srv = static_cast<DataServer*>(argp);
  srv->active.fetch_add(1);
  for (;;) {
    int cfd = accept(srv->lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR && !srv->stopping.load()) continue;
      break;  // listener closed (stop() or process exit)
    }
    if (srv->stopping.load()) {
      close(cfd);
      break;
    }
    auto* carg = new (std::nothrow) ConnArg{srv, cfd};
    if (!carg) {
      close(cfd);
      continue;
    }
    srv->active.fetch_add(1);
    pthread_t tid;
    pthread_attr_t attr;
    pthread_attr_init(&attr);
    pthread_attr_setdetachstate(&attr, PTHREAD_CREATE_DETACHED);
    if (pthread_create(&tid, &attr, conn_main, carg) != 0) {
      close(cfd);
      delete carg;
      srv->active.fetch_sub(1);
    }
    pthread_attr_destroy(&attr);
  }
  srv->active.fetch_sub(1);
  return nullptr;
}

}  // namespace

extern "C" {

// Start serving `store` on TCP `port` (0 = ephemeral). Writes the bound
// port to *out_port. Returns an opaque handle for store_data_server_stop,
// or nullptr on error.
void* store_data_server_start(Store* s, int port, int* out_port) {
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return nullptr;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(lfd, 64) != 0) {
    close(lfd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  if (getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen) != 0) {
    close(lfd);
    return nullptr;
  }
  auto* srv = new (std::nothrow) DataServer{};
  if (!srv) {
    close(lfd);
    return nullptr;
  }
  srv->store = s;
  srv->lfd = lfd;
  pthread_t tid;
  pthread_attr_t attr;
  pthread_attr_init(&attr);
  pthread_attr_setdetachstate(&attr, PTHREAD_CREATE_DETACHED);
  if (pthread_create(&tid, &attr, accept_main, srv) != 0) {
    close(lfd);
    delete srv;
    pthread_attr_destroy(&attr);
    return nullptr;
  }
  pthread_attr_destroy(&attr);
  if (out_port) *out_port = ntohs(addr.sin_port);
  return srv;
}

// Stop the server and JOIN (spin-wait) every serving thread so the Store
// can be safely destroyed afterwards. Waits at most ~5s; the handle leaks
// (never freed) if threads are wedged past that — deliberate: freeing it
// under a live thread would be the use-after-free we're preventing.
int store_data_server_stop(void* handle) {
  auto* srv = static_cast<DataServer*>(handle);
  if (!srv) return -1;
  srv->stopping.store(true);
  shutdown(srv->lfd, SHUT_RDWR);
  close(srv->lfd);
  pthread_mutex_lock(&srv->mu);
  for (int fd : srv->conns) shutdown(fd, SHUT_RDWR);
  pthread_mutex_unlock(&srv->mu);
  for (int i = 0; i < 5000 && srv->active.load() > 0; ++i) {
    usleep(1000);
  }
  if (srv->active.load() == 0) {
    delete srv;
    return 0;
  }
  return -1;
}

}  // extern "C"
