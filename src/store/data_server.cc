// Native object data-plane server.
//
// Serves sealed objects from the shared-memory store over TCP with zero
// Python involvement on the serving side — the TPU-native analog of the
// reference's C++ ObjectManager chunk transfer
// (/root/reference/src/ray/object_manager/object_manager.h, chunked gRPC
// push/pull). The Python raylet keeps orchestrating WHICH objects move
// (locations, admission control); the bytes themselves are read out of
// the mmap'd segment and written to the socket by these threads, GIL-free.
//
// Wire protocol (all integers little-endian):
//   request : 16-byte object id | uint64 offset | uint64 max_length
//   response: uint64 total_size | uint64 payload_length | payload bytes
//             total_size == UINT64_MAX  => object not present (sealed) here
// Connections are persistent; one request/response at a time per
// connection (pullers pipeline by chunking sequentially, like the
// reference's per-chunk gRPC calls).

#include <cerrno>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <new>

struct Store;  // opaque; defined in store.cc (same translation library)

extern "C" {
int store_get(Store* s, const uint8_t* id, void** out_ptr,
              uint64_t* out_size);
int store_release(Store* s, const uint8_t* id);
}

namespace {

constexpr uint64_t kMissing = ~0ull;
constexpr size_t kReqSize = 32;  // 16B id + 8B offset + 8B length

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w < 0 && errno == EINTR) continue;
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

struct ConnArg {
  Store* store;
  int fd;
};

void* conn_main(void* argp) {
  ConnArg* arg = static_cast<ConnArg*>(argp);
  Store* store = arg->store;
  int fd = arg->fd;
  delete arg;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Bounded sends: a hung/stopped puller must not pin the object (the
  // store_get refcount) forever — after the timeout the write fails, the
  // pin is released and the thread exits.
  timeval send_timeout{120, 0};
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &send_timeout,
             sizeof(send_timeout));
  uint8_t req[kReqSize];
  while (read_full(fd, req, kReqSize)) {
    uint64_t offset, max_len;
    memcpy(&offset, req + 16, 8);
    memcpy(&max_len, req + 24, 8);
    void* ptr = nullptr;
    uint64_t size = 0;
    uint64_t hdr[2];
    if (store_get(store, req, &ptr, &size) != 0) {
      hdr[0] = kMissing;
      hdr[1] = 0;
      if (!write_full(fd, hdr, sizeof(hdr))) break;
      continue;
    }
    uint64_t n = 0;
    if (offset < size) {
      n = size - offset;
      if (n > max_len) n = max_len;
    }
    hdr[0] = size;
    hdr[1] = n;
    bool ok = write_full(fd, hdr, sizeof(hdr)) &&
              (n == 0 ||
               write_full(fd, static_cast<uint8_t*>(ptr) + offset, n));
    store_release(store, req);
    if (!ok) break;
  }
  close(fd);
  return nullptr;
}

struct SrvArg {
  Store* store;
  int lfd;
};

void* accept_main(void* argp) {
  SrvArg* arg = static_cast<SrvArg*>(argp);
  for (;;) {
    int cfd = accept(arg->lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR) continue;
      break;  // listener closed (process exit)
    }
    auto* carg = new (std::nothrow) ConnArg{arg->store, cfd};
    if (!carg) {
      close(cfd);
      continue;
    }
    pthread_t tid;
    pthread_attr_t attr;
    pthread_attr_init(&attr);
    pthread_attr_setdetachstate(&attr, PTHREAD_CREATE_DETACHED);
    if (pthread_create(&tid, &attr, conn_main, carg) != 0) {
      close(cfd);
      delete carg;
    }
    pthread_attr_destroy(&attr);
  }
  close(arg->lfd);
  delete arg;
  return nullptr;
}

}  // namespace

extern "C" {

// Start serving `store` on TCP `port` (0 = ephemeral). Returns the bound
// port, or -1 on error. The server runs detached until process exit.
int store_data_server_start(Store* s, int port) {
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return -1;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(lfd, 64) != 0) {
    close(lfd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  if (getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen) != 0) {
    close(lfd);
    return -1;
  }
  auto* arg = new (std::nothrow) SrvArg{s, lfd};
  if (!arg) {
    close(lfd);
    return -1;
  }
  pthread_t tid;
  pthread_attr_t attr;
  pthread_attr_init(&attr);
  pthread_attr_setdetachstate(&attr, PTHREAD_CREATE_DETACHED);
  if (pthread_create(&tid, &attr, accept_main, arg) != 0) {
    close(lfd);
    delete arg;
    pthread_attr_destroy(&attr);
    return -1;
  }
  pthread_attr_destroy(&attr);
  return ntohs(addr.sin_port);
}

}  // extern "C"
