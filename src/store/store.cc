// raystore — shared-memory immutable object store (TPU-host analog of the
// reference's plasma store: /root/reference/src/ray/object_manager/plasma/
// store.cc, shared_memory.cc, eviction_policy.cc). Unlike plasma (a daemon
// reached over a unix socket with fd-passing), this store is a *library*:
// every process on the node maps the same POSIX shm segment and coordinates
// through a robust process-shared mutex living inside the segment. That
// removes the socket round-trip from the put/get hot path entirely — the
// driver/worker hot loop touches only shared memory.
//
// Layout of the segment:
//   [ Header | ObjectEntry table (n_slots) | data heap ... ]
// All references inside the segment are offsets (processes map at different
// addresses). Allocation is first-fit over an embedded free list with
// coalescing on free. Eviction is LRU over sealed, refcount==0 objects.
//
// Exposed as a C ABI for ctypes (python binding:
// ray_tpu/_private/store_client.py).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52415953544f5245ULL;  // "RAYSTORE"
constexpr uint32_t kIdSize = 16;
constexpr uint64_t kAlign = 64;  // cacheline-align object payloads

enum ErrorCode : int {
  OK = 0,
  ERR_NOT_FOUND = -1,
  ERR_EXISTS = -2,
  ERR_FULL = -3,
  ERR_TABLE_FULL = -4,
  ERR_NOT_SEALED = -5,
  ERR_IN_USE = -6,
  ERR_SYS = -7,
  ERR_BAD_SEGMENT = -8,
};

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint64_t data_off;   // offset of payload from segment base
  uint64_t data_size;  // payload bytes
  uint64_t lru_tick;   // last-access logical clock
  int32_t refcount;    // pinned readers/writers
  uint8_t state;       // 0 free, 1 creating, 2 sealed
  uint8_t _pad[3];
};

// Free-list node embedded in the heap itself.
struct FreeBlock {
  uint64_t size;      // bytes including this header
  uint64_t next_off;  // offset of next free block (0 = end)
};

struct Header {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t heap_off;    // start of data heap
  uint64_t heap_size;   // bytes in heap
  uint64_t free_head;   // offset of first free block (0 = none)
  uint64_t n_slots;     // object table capacity
  uint64_t n_objects;   // live (creating+sealed) objects
  uint64_t bytes_used;  // payload bytes allocated
  uint64_t lru_clock;   // logical tick for LRU
  uint64_t evictions;   // stat: objects evicted
  pthread_mutex_t mutex;
  // ObjectEntry table follows immediately.
};

struct Store {
  void* base;
  uint64_t size;
  int fd;
  char name[256];
};

inline Header* header(Store* s) { return reinterpret_cast<Header*>(s->base); }
inline ObjectEntry* table(Store* s) {
  return reinterpret_cast<ObjectEntry*>(static_cast<char*>(s->base) +
                                        sizeof(Header));
}
inline char* at(Store* s, uint64_t off) {
  return static_cast<char*>(s->base) + off;
}

uint64_t id_hash(const uint8_t* id) {
  // FNV-1a over the 16-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// Lock with robust-mutex recovery: if a worker died holding the lock, take
// ownership and mark state consistent (the table stays valid because all
// mutations are idempotent-ordered: sizes are written before state flips).
int lock(Store* s) {
  int rc = pthread_mutex_lock(&header(s)->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&header(s)->mutex);
    return 0;
  }
  return rc;
}
void unlock(Store* s) { pthread_mutex_unlock(&header(s)->mutex); }

// Find the table slot for `id`, or the first free slot if absent
// (linear probing; n_slots is a power of two).
ObjectEntry* find_slot(Store* s, const uint8_t* id, bool want_free) {
  Header* h = header(s);
  ObjectEntry* t = table(s);
  uint64_t mask = h->n_slots - 1;
  uint64_t idx = id_hash(id) & mask;
  ObjectEntry* first_free = nullptr;
  for (uint64_t probe = 0; probe < h->n_slots; probe++) {
    ObjectEntry* e = &t[(idx + probe) & mask];
    if (e->state == 0) {
      if (!want_free) return nullptr;   // empty slot ends the probe chain
      return first_free ? first_free : e;
    }
    if (memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return first_free;
}

// NOTE: deletion uses tombstone-free compaction via full probe; to keep the
// implementation simple we never shrink chains — instead lookups stop at the
// first state==0 slot, so delete re-inserts any chain successors. To avoid
// that complexity entirely, deleted slots keep state==0 only when safe; we
// simply rehash the successors of the deleted slot.
void fixup_chain(Store* s, uint64_t hole_idx) {
  Header* h = header(s);
  ObjectEntry* t = table(s);
  uint64_t mask = h->n_slots - 1;
  uint64_t idx = (hole_idx + 1) & mask;
  while (t[idx].state != 0) {
    ObjectEntry moved = t[idx];
    t[idx].state = 0;
    ObjectEntry* dst = find_slot(s, moved.id, true);
    *dst = moved;
    idx = (idx + 1) & mask;
  }
}

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

// Each allocation is prefixed by a kAlign-byte header whose first 8 bytes
// record the true block size (which may exceed the rounded request when a
// free-list remainder too small to split is absorbed). Payloads thus stay
// cacheline-aligned and frees are exact.
//
// First-fit allocate from the free list. Returns *payload* offset or 0.
uint64_t heap_alloc(Store* s, uint64_t need) {
  Header* h = header(s);
  need = align_up(need < kAlign ? kAlign : need, kAlign) + kAlign;
  uint64_t prev_off = 0;
  uint64_t off = h->free_head;
  while (off) {
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(at(s, off));
    if (fb->size >= need) {
      uint64_t remain = fb->size - need;
      uint64_t got = need;
      if (remain >= sizeof(FreeBlock) + 2 * kAlign) {
        // split: tail remains free
        uint64_t tail_off = off + need;
        FreeBlock* tail = reinterpret_cast<FreeBlock*>(at(s, tail_off));
        tail->size = remain;
        tail->next_off = fb->next_off;
        if (prev_off)
          reinterpret_cast<FreeBlock*>(at(s, prev_off))->next_off = tail_off;
        else
          h->free_head = tail_off;
      } else {
        got = fb->size;  // absorb the remainder
        if (prev_off)
          reinterpret_cast<FreeBlock*>(at(s, prev_off))->next_off = fb->next_off;
        else
          h->free_head = fb->next_off;
      }
      h->bytes_used += got;
      *reinterpret_cast<uint64_t*>(at(s, off)) = got;
      return off + kAlign;
    }
    prev_off = off;
    off = fb->next_off;
  }
  return 0;
}

// Free a payload offset returned by heap_alloc; exact size comes from the
// allocation header. Address-ordered insert + coalescing.
void heap_free(Store* s, uint64_t payload_off, uint64_t /*unused*/) {
  Header* h = header(s);
  uint64_t off = payload_off - kAlign;
  uint64_t size = *reinterpret_cast<uint64_t*>(at(s, off));
  h->bytes_used -= size;
  uint64_t prev_off = 0, cur = h->free_head;
  while (cur && cur < off) {
    prev_off = cur;
    cur = reinterpret_cast<FreeBlock*>(at(s, cur))->next_off;
  }
  FreeBlock* fb = reinterpret_cast<FreeBlock*>(at(s, off));
  fb->size = size;
  fb->next_off = cur;
  if (prev_off) {
    FreeBlock* prev = reinterpret_cast<FreeBlock*>(at(s, prev_off));
    prev->next_off = off;
    if (prev_off + prev->size == off) {  // coalesce with prev
      prev->size += fb->size;
      prev->next_off = fb->next_off;
      fb = prev;
      off = prev_off;
    }
  } else {
    h->free_head = off;
  }
  if (cur && off + fb->size == cur) {  // coalesce with next
    FreeBlock* next = reinterpret_cast<FreeBlock*>(at(s, cur));
    fb->size += next->size;
    fb->next_off = next->next_off;
  }
}

// Evict up to `count` LRU sealed refcount-0 objects (used to relieve table
// pressure). Returns number evicted. Caller holds the lock.
int evict_n(Store* s, int count) {
  Header* h = header(s);
  ObjectEntry* t = table(s);
  int evicted = 0;
  for (int rounds = 0; rounds < count; rounds++) {
    ObjectEntry* victim = nullptr;
    for (uint64_t i = 0; i < h->n_slots; i++) {
      ObjectEntry* e = &t[i];
      if (e->state == 2 && e->refcount == 0) {
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (!victim) return evicted;
    heap_free(s, victim->data_off, victim->data_size);
    uint64_t idx = victim - t;
    victim->state = 0;
    h->n_objects--;
    h->evictions++;
    evicted++;
    fixup_chain(s, idx);
  }
  return evicted;
}

uint64_t table_bytes(uint64_t n_slots) { return n_slots * sizeof(ObjectEntry); }

int init_segment(Store* s, uint64_t size, uint64_t n_slots) {
  Header* h = header(s);
  memset(h, 0, sizeof(Header));
  h->magic = kMagic;
  h->segment_size = size;
  h->n_slots = n_slots;
  memset(table(s), 0, table_bytes(n_slots));
  uint64_t heap_off = align_up(sizeof(Header) + table_bytes(n_slots), kAlign);
  h->heap_off = heap_off;
  h->heap_size = size - heap_off;
  FreeBlock* fb = reinterpret_cast<FreeBlock*>(at(s, heap_off));
  fb->size = h->heap_size;
  fb->next_off = 0;
  h->free_head = heap_off;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  if (pthread_mutex_init(&h->mutex, &attr) != 0) return ERR_SYS;
  pthread_mutexattr_destroy(&attr);
  return OK;
}

}  // namespace

extern "C" {

// Create a new store segment (unlinks any stale one first). n_slots must be
// a power of two. Returns an opaque handle or nullptr.
Store* store_create(const char* name, uint64_t size, uint64_t n_slots) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Store* s = new Store{base, size, fd, {0}};
  strncpy(s->name, name, sizeof(s->name) - 1);
  if (init_segment(s, size, n_slots) != OK) {
    munmap(base, size);
    close(fd);
    shm_unlink(name);
    delete s;
    return nullptr;
  }
  return s;
}

// Connect to an existing segment created by another process.
Store* store_connect(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store{base, static_cast<uint64_t>(st.st_size), fd, {0}};
  strncpy(s->name, name, sizeof(s->name) - 1);
  if (header(s)->magic != kMagic) {
    munmap(base, s->size);
    close(fd);
    delete s;
    return nullptr;
  }
  return s;
}

void store_disconnect(Store* s) {
  if (!s) return;
  munmap(s->base, s->size);
  close(s->fd);
  delete s;
}

// Destroy the segment (owner only).
void store_destroy(Store* s) {
  if (!s) return;
  char name[256];
  strncpy(name, s->name, sizeof(name));
  store_disconnect(s);
  shm_unlink(name);
}

// Begin creating an object: allocates space, returns a writable pointer via
// *out_ptr (valid in this process). Object is invisible to get() until
// sealed. Evicts LRU objects if needed.
int store_create_object(Store* s, const uint8_t* id, uint64_t size,
                        void** out_ptr) {
  if (lock(s) != 0) return ERR_SYS;
  Header* h = header(s);
  // An object that can NEVER fit must not trigger the eviction loop:
  // without this check a single oversized create evicted every unpinned
  // object (each victim an O(n_slots) scan under the cross-process
  // lock) and still failed — mass data eviction + quadratic latency for
  // nothing. The caller spills oversized objects to disk instead.
  // The 128-byte headroom mirrors heap_alloc's worst-case alignment +
  // block-header overhead, so near-heap-size objects short-circuit too.
  if (size + 128 > h->heap_size) {
    unlock(s);
    return ERR_FULL;
  }
  ObjectEntry* existing = find_slot(s, id, false);
  if (existing) {
    unlock(s);
    return ERR_EXISTS;
  }
  if (h->n_objects >= h->n_slots - (h->n_slots >> 2)) {  // keep table <75% full
    evict_n(s, 16);
    if (h->n_objects >= h->n_slots - (h->n_slots >> 2)) {
      unlock(s);
      return ERR_TABLE_FULL;
    }
  }
  uint64_t off = heap_alloc(s, size);
  while (!off) {
    // Evict one LRU victim and retry.
    ObjectEntry* t = table(s);
    ObjectEntry* victim = nullptr;
    for (uint64_t i = 0; i < h->n_slots; i++) {
      ObjectEntry* e = &t[i];
      if (e->state == 2 && e->refcount == 0)
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
    }
    if (!victim) {
      unlock(s);
      return ERR_FULL;
    }
    heap_free(s, victim->data_off, victim->data_size);
    uint64_t idx = victim - t;
    victim->state = 0;
    h->n_objects--;
    h->evictions++;
    fixup_chain(s, idx);
    off = heap_alloc(s, size);
  }
  ObjectEntry* e = find_slot(s, id, true);
  if (!e) {  // shouldn't happen after the capacity check
    heap_free(s, off, size);
    unlock(s);
    return ERR_TABLE_FULL;
  }
  memcpy(e->id, id, kIdSize);
  e->data_off = off;
  e->data_size = size;
  e->refcount = 1;  // creator holds a pin until seal/abort
  e->state = 1;
  e->lru_tick = ++h->lru_clock;
  h->n_objects++;
  *out_ptr = at(s, off);
  unlock(s);
  return OK;
}

// Seal: object becomes immutable + visible. Drops the creator pin.
int store_seal(Store* s, const uint8_t* id) {
  if (lock(s) != 0) return ERR_SYS;
  ObjectEntry* e = find_slot(s, id, false);
  if (!e) {
    unlock(s);
    return ERR_NOT_FOUND;
  }
  if (e->state != 1) {
    unlock(s);
    return ERR_NOT_SEALED;
  }
  e->state = 2;
  e->refcount--;
  unlock(s);
  return OK;
}

// Abort an in-progress create.
int store_abort(Store* s, const uint8_t* id) {
  if (lock(s) != 0) return ERR_SYS;
  ObjectEntry* e = find_slot(s, id, false);
  if (!e || e->state != 1) {
    unlock(s);
    return ERR_NOT_FOUND;
  }
  heap_free(s, e->data_off, e->data_size);
  uint64_t idx = e - table(s);
  e->state = 0;
  header(s)->n_objects--;
  fixup_chain(s, idx);
  unlock(s);
  return OK;
}

// Get a sealed object: pins it (refcount++) and returns pointer + size.
int store_get(Store* s, const uint8_t* id, void** out_ptr,
              uint64_t* out_size) {
  if (lock(s) != 0) return ERR_SYS;
  ObjectEntry* e = find_slot(s, id, false);
  if (!e || e->state != 2) {
    unlock(s);
    return ERR_NOT_FOUND;
  }
  e->refcount++;
  e->lru_tick = ++header(s)->lru_clock;
  *out_ptr = at(s, e->data_off);
  *out_size = e->data_size;
  unlock(s);
  return OK;
}

// Release a pin taken by store_get.
int store_release(Store* s, const uint8_t* id) {
  if (lock(s) != 0) return ERR_SYS;
  ObjectEntry* e = find_slot(s, id, false);
  if (!e) {
    unlock(s);
    return ERR_NOT_FOUND;
  }
  if (e->refcount > 0) e->refcount--;
  unlock(s);
  return OK;
}

int store_contains(Store* s, const uint8_t* id) {
  if (lock(s) != 0) return ERR_SYS;
  ObjectEntry* e = find_slot(s, id, false);
  int rc = (e && e->state == 2) ? 1 : 0;
  unlock(s);
  return rc;
}

// Delete a sealed object (fails with ERR_IN_USE if pinned).
int store_delete(Store* s, const uint8_t* id) {
  if (lock(s) != 0) return ERR_SYS;
  ObjectEntry* e = find_slot(s, id, false);
  if (!e || e->state != 2) {
    unlock(s);
    return ERR_NOT_FOUND;
  }
  if (e->refcount > 0) {
    unlock(s);
    return ERR_IN_USE;
  }
  heap_free(s, e->data_off, e->data_size);
  uint64_t idx = e - table(s);
  e->state = 0;
  header(s)->n_objects--;
  fixup_chain(s, idx);
  unlock(s);
  return OK;
}

// List sealed objects: writes up to `max` (id, size) rows into out_ids
// (max*16 bytes) / out_sizes (max entries); returns the number written,
// or ERR_SYS. Powers `ray-tpu memory` under the owner-based directory —
// per-node store contents replace the retired central location table.
int store_list(Store* s, uint8_t* out_ids, uint64_t* out_sizes,
               uint64_t max) {
  if (lock(s) != 0) return ERR_SYS;
  Header* h = header(s);
  ObjectEntry* t = table(s);
  uint64_t n = 0;
  for (uint64_t i = 0; i < h->n_slots && n < max; i++) {
    if (t[i].state != 2) continue;
    memcpy(out_ids + n * kIdSize, t[i].id, kIdSize);
    out_sizes[n] = t[i].data_size;
    n++;
  }
  unlock(s);
  return static_cast<int>(n);
}

// Stats: fills [n_objects, bytes_used, heap_size, evictions].
int store_stats(Store* s, uint64_t* out4) {
  if (lock(s) != 0) return ERR_SYS;
  Header* h = header(s);
  out4[0] = h->n_objects;
  out4[1] = h->bytes_used;
  out4[2] = h->heap_size;
  out4[3] = h->evictions;
  unlock(s);
  return OK;
}

}  // extern "C"
