// Sanitizer stress driver for the native runtime components.
//
// Reference analog: the asan/tsan-tagged stress configs of the
// reference's test BUILD (python/ray/tests/BUILD asan tags) — here a
// standalone C++ binary so ThreadSanitizer/AddressSanitizer see fully
// instrumented code without an instrumented Python.
//
//   stress_native store      — multi-thread + multi-process segment abuse
//   stress_native rpc        — echo server vs N hammering client threads
//   stress_native dataserver — concurrent range pulls during churn
//
// Exit 0 = workload completed; sanitizer findings fail the run via the
// sanitizer's own exit code (halt_on_error).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// ---- store C API (store.cc) ------------------------------------------------
struct Store;
extern "C" {
Store* store_create(const char* name, uint64_t size, uint64_t n_slots);
Store* store_connect(const char* name);
void store_disconnect(Store* s);
void store_destroy(Store* s);
int store_create_object(Store* s, const uint8_t* id, uint64_t size,
                        void** out_ptr);
int store_seal(Store* s, const uint8_t* id);
int store_abort(Store* s, const uint8_t* id);
int store_get(Store* s, const uint8_t* id, void** out_ptr,
              uint64_t* out_size);
int store_release(Store* s, const uint8_t* id);
int store_contains(Store* s, const uint8_t* id);
int store_delete(Store* s, const uint8_t* id);
int store_stats(Store* s, uint64_t* out4);
void* store_data_server_start(Store* s, int port, int* out_port);
int store_data_server_stop(void* h);
// rpc C API (rpc_core.cc)
void rpc_buf_free(char* buf);
void* rpc_cl_connect(const char* host, int port, int timeout_ms);
int rpc_cl_send(void* h, int kind, long long seq, const char* buf,
                size_t len, int expect_sync);
int rpc_cl_wait(void* h, long long seq, int timeout_ms, char** out,
                size_t* out_len);
void rpc_cl_close(void* h);
void* rpc_sv_start(const char* host, int port);
int rpc_sv_port(void* h);
int rpc_sv_next(void* h, int timeout_ms, unsigned long long* conn_id,
                int* kind, long long* seq, char** out, size_t* out_len);
int rpc_sv_send(void* h, unsigned long long conn_id, int kind,
                long long seq, const char* buf, size_t len);
void rpc_sv_stop(void* h);
}

namespace {

uint64_t splitmix(uint64_t& x) {
  x += 0x9E3779B97F4A7C15ull;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

void make_id(uint64_t a, uint64_t b, uint8_t* out) {
  memcpy(out, &a, 8);
  memcpy(out + 8, &b, 8);
}

// ---- store stress ----------------------------------------------------------

void store_worker(Store* s, int tid, int iters, std::atomic<int>* errors) {
  uint64_t rng = 0xC0FFEE + tid;
  for (int i = 0; i < iters; i++) {
    uint8_t id[16];
    make_id(tid, splitmix(rng) % 64, id);
    uint64_t size = 64 + splitmix(rng) % 8192;
    void* ptr = nullptr;
    int rc = store_create_object(s, id, size, &ptr);
    if (rc == 0) {
      memset(ptr, static_cast<int>(size & 0xFF), size);
      if (store_seal(s, id) != 0) errors->fetch_add(1);
    }
    void* got = nullptr;
    uint64_t got_size = 0;
    if (store_get(s, id, &got, &got_size) == 0) {
      // validate a sample byte while pinned (races with eviction would
      // show as tsan findings or wrong bytes)
      volatile uint8_t v = static_cast<uint8_t*>(got)[got_size / 2];
      if (v != static_cast<uint8_t>(got_size & 0xFF)) errors->fetch_add(1);
      store_release(s, id);
    }
    if (splitmix(rng) % 7 == 0) store_delete(s, id);
    if (splitmix(rng) % 31 == 0) {
      // eviction pressure: big object forces the LRU loop
      uint8_t big[16];
      make_id(0xB16, tid, big);
      void* bp = nullptr;
      if (store_create_object(s, big, 512 * 1024, &bp) == 0) {
        store_seal(s, big);
        store_delete(s, big);
      }
    }
  }
}

int run_store(int iters) {
  char name[64];
  snprintf(name, sizeof(name), "stress-%d", getpid());
  Store* s = store_create(name, 8 * 1024 * 1024, 4096);
  if (!s) {
    fprintf(stderr, "store_create failed\n");
    return 1;
  }
  std::atomic<int> errors{0};
  // cross-process contention: forked children attach by name (the
  // robust-mutex + shared free-list paths). Fork BEFORE spawning the
  // in-process threads: a child must inherit a single-threaded image,
  // both for POSIX fork semantics and because tsan's thread registry
  // is copied into the child — parent threads the child can never join
  // would otherwise report as thread leaks at the child's _exit.
  std::vector<pid_t> kids;
  for (int p = 0; p < 2; p++) {
    pid_t pid = fork();
    if (pid == 0) {
      Store* cs = store_connect(name);
      if (!cs) _exit(2);
      std::atomic<int> cerr{0};
      store_worker(cs, 100 + p, iters, &cerr);
      store_disconnect(cs);
      _exit(cerr.load() ? 3 : 0);
    }
    kids.push_back(pid);
  }
  // in-process threads
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++)
    ts.emplace_back(store_worker, s, t, iters, &errors);
  for (auto& t : ts) t.join();
  int fail = 0;
  for (pid_t pid : kids) {
    int st = 0;
    waitpid(pid, &st, 0);
    if (!WIFEXITED(st) || WEXITSTATUS(st) != 0) fail++;
  }
  uint64_t out4[4];
  store_stats(s, out4);
  fprintf(stderr, "store: objects=%llu used=%llu evictions=%llu "
          "errors=%d child_fail=%d\n",
          (unsigned long long)out4[0], (unsigned long long)out4[1],
          (unsigned long long)out4[3], errors.load(), fail);
  store_destroy(s);
  return (errors.load() || fail) ? 1 : 0;
}

// ---- rpc stress ------------------------------------------------------------

int run_rpc(int iters) {
  void* sv = rpc_sv_start("127.0.0.1", 0);
  if (!sv) return 1;
  int port = rpc_sv_port(sv);
  std::atomic<bool> stop{false};
  std::thread server([&] {
    // echo loop: REQUEST (kind 0) -> REPLY (kind 1) with the same bytes
    while (!stop.load()) {
      unsigned long long conn = 0;
      int kind = 0;
      long long seq = 0;
      char* buf = nullptr;
      size_t len = 0;
      int rc = rpc_sv_next(sv, 50, &conn, &kind, &seq, &buf, &len);
      if (rc == 2) break;
      if (rc != 0) continue;
      if (kind == 0) rpc_sv_send(sv, conn, 1, seq, buf, len);
      if (buf) rpc_buf_free(buf);
    }
  });
  std::atomic<int> errors{0};
  std::vector<std::thread> clients;
  for (int c = 0; c < 6; c++) {
    clients.emplace_back([&, c] {
      void* cl = rpc_cl_connect("127.0.0.1", port, 30000);
      if (!cl) {
        errors.fetch_add(1);
        return;
      }
      uint64_t rng = 0xABCD + c;
      std::string payload;
      for (int i = 1; i <= iters; i++) {
        payload.assign(1 + splitmix(rng) % 70000,
                       static_cast<char>('a' + (i % 26)));
        if (rpc_cl_send(cl, 0, i, payload.data(), payload.size(), 1) != 0) {
          fprintf(stderr, "client %d iter %d: send failed\n", c, i);
          errors.fetch_add(1);
          break;
        }
        char* out = nullptr;
        size_t out_len = 0;
        int wrc = rpc_cl_wait(cl, i, 120000, &out, &out_len);
        if (wrc != 0 || out_len != payload.size() ||
            memcmp(out, payload.data(), out_len) != 0) {
          fprintf(stderr, "client %d iter %d: wait rc=%d len=%zu "
                  "want=%zu\n", c, i, wrc, out_len, payload.size());
          errors.fetch_add(1);
          if (out) rpc_buf_free(out);
          break;
        }
        rpc_buf_free(out);
      }
      rpc_cl_close(cl);
    });
  }
  for (auto& t : clients) t.join();
  stop.store(true);
  rpc_sv_stop(sv);
  server.join();
  fprintf(stderr, "rpc: errors=%d\n", errors.load());
  return errors.load() ? 1 : 0;
}

// ---- data-server stress ----------------------------------------------------

bool pull_once(int port, const uint8_t* id, uint64_t offset,
               uint64_t max_len, std::string* out) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return false;
  }
  uint8_t req[32];
  memcpy(req, id, 16);
  memcpy(req + 16, &offset, 8);
  memcpy(req + 24, &max_len, 8);
  auto wr = [&](const void* p, size_t n) {
    const char* c = static_cast<const char*>(p);
    while (n) {
      ssize_t w = write(fd, c, n);
      if (w <= 0) return false;
      c += w;
      n -= static_cast<size_t>(w);
    }
    return true;
  };
  auto rd = [&](void* p, size_t n) {
    char* c = static_cast<char*>(p);
    while (n) {
      ssize_t r = read(fd, c, n);
      if (r <= 0) return false;
      c += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  };
  bool ok = false;
  uint64_t hdr[2];
  if (wr(req, sizeof(req)) && rd(hdr, sizeof(hdr)) &&
      hdr[0] != ~0ull) {
    out->resize(hdr[1]);
    ok = hdr[1] == 0 || rd(&(*out)[0], hdr[1]);
  }
  close(fd);
  return ok;
}

int run_dataserver(int iters) {
  char name[64];
  snprintf(name, sizeof(name), "dstress-%d", getpid());
  Store* s = store_create(name, 16 * 1024 * 1024, 1024);
  if (!s) return 1;
  int port = 0;
  void* srv = store_data_server_start(s, 0, &port);
  if (!srv) {
    store_destroy(s);
    return 1;
  }
  // seed objects
  const int kObjects = 16;
  for (int i = 0; i < kObjects; i++) {
    uint8_t id[16];
    make_id(0xDA7A, i, id);
    void* ptr = nullptr;
    uint64_t size = 4096 * (1 + i);
    if (store_create_object(s, id, size, &ptr) == 0) {
      memset(ptr, i, size);
      store_seal(s, id);
    }
  }
  std::atomic<int> errors{0};
  std::vector<std::thread> pullers;
  for (int c = 0; c < 4; c++) {
    pullers.emplace_back([&, c] {
      uint64_t rng = 0xD00D + c;
      for (int i = 0; i < iters; i++) {
        int oi = static_cast<int>(splitmix(rng) % kObjects);
        uint8_t id[16];
        make_id(0xDA7A, oi, id);
        uint64_t size = 4096 * (1 + oi);
        uint64_t off = splitmix(rng) % size;
        std::string out;
        if (pull_once(port, id, off, 2048, &out)) {
          for (char ch : out)
            if (static_cast<uint8_t>(ch) != oi) {
              errors.fetch_add(1);
              break;
            }
        }
      }
    });
  }
  // churn: rewrite objects while pulls stream (delete + recreate)
  std::thread churn([&] {
    uint64_t rng = 0xC4C4;
    for (int i = 0; i < iters; i++) {
      int oi = static_cast<int>(splitmix(rng) % kObjects);
      uint8_t id[16];
      make_id(0xDA7A, oi, id);
      store_delete(s, id);
      void* ptr = nullptr;
      uint64_t size = 4096 * (1 + oi);
      if (store_create_object(s, id, size, &ptr) == 0) {
        memset(ptr, oi, size);
        store_seal(s, id);
      }
    }
  });
  for (auto& t : pullers) t.join();
  churn.join();
  store_data_server_stop(srv);
  fprintf(stderr, "dataserver: errors=%d\n", errors.load());
  store_destroy(s);
  return errors.load() ? 1 : 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s store|rpc|dataserver [iters]\n", argv[0]);
    return 64;
  }
  int iters = argc > 2 ? atoi(argv[2]) : 2000;
  std::string mode = argv[1];
  if (mode == "store") return run_store(iters);
  if (mode == "rpc") return run_rpc(iters);
  if (mode == "dataserver") return run_dataserver(iters);
  fprintf(stderr, "unknown mode %s\n", mode.c_str());
  return 64;
}
