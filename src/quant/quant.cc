// Block-quantized wire kernels for the pipelined host collectives
// (ray_tpu/util/collective/wire.py loads this as librayquant.so; every
// entry point has a numpy fallback there, so a missing compiler only
// costs speed, never correctness).
//
// Why C: the quantize/dequantize passes sit INSIDE the ring's
// per-segment budget — at 4 MiB segments the whole point of sending
// 1/4 of the bytes dies if the encode costs more than the bytes it
// saves. numpy needs one full temporary pass per step (abs, max,
// multiply, round, cast: ~0.4 ms/MB); these fused single-pass loops
// auto-vectorize to ~0.07 ms/MB.
//
// Numerics contract (mirrored by the numpy fallback and pinned by
// tests/test_zz_quant_collectives.py):
//   int8:  per-block scale = absmax/127, round half away from zero;
//          |deq(x) - x| <= absmax_block/254 (half a quantization step).
//          Any non-finite value in the input returns 1 and the caller
//          falls back to the exact wire format for the whole segment.
//   bf16:  round-to-nearest-even on the high 16 bits; NaN payloads are
//          truncated with the quiet bit forced (a rounded NaN mantissa
//          could carry into the exponent and come back +-inf), Inf is
//          preserved exactly; |deq(x) - x| <= 2^-8 * |x|.

#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

// The decode-accumulate family writes a fresh result buffer the size of
// the PAYLOAD (4x the wire bytes for int8) — under two ranks contending
// for memory bandwidth those read-for-ownership fills are a third of
// the traffic. When the destination is 32-byte aligned (host_backend
// allocates acc that way in wire mode) the AVX2 paths use non-temporal
// streaming stores instead. Every vector path computes mul+mul+add /
// mul+add EXACTLY like the scalar loops (no FMA — see the
// -ffp-contract=off note in native_build.py), so results stay
// bit-identical whichever path runs.

#if defined(__AVX2__)
static inline __m256 dq8(const int8_t* p, __m256 scale) {
  return _mm256_mul_ps(
      _mm256_cvtepi32_ps(_mm256_cvtepi8_epi32(
          _mm_loadl_epi64((const __m128i*)p))),
      scale);
}

static inline __m256 dqbf16(const uint16_t* p) {
  __m128i h = _mm_loadu_si128((const __m128i*)p);
  return _mm256_castsi256_ps(
      _mm256_slli_epi32(_mm256_cvtepu16_epi32(h), 16));
}
#endif

static inline int aligned32(const void* p) {
  return (((uintptr_t)p) & 31u) == 0;
}

extern "C" {

// ---------------------------------------------------------------- int8

// absmax runs over the uint32 domain: for IEEE-754 floats,
// |a| <= |b|  iff  (bits(a) & 0x7FFFFFFF) <= (bits(b) & 0x7FFFFFFF),
// and NaN/Inf sort above every finite value — one integer max both
// finds the block scale and detects non-finite input. Integer max
// reductions vectorize without -ffast-math (no FP reassociation), the
// float version does not.
int rq_enc_i8(const float* x, int64_t n, int64_t block,
              float* scales, int8_t* q) {
  int64_t nb = n / block;
  for (int64_t b = 0; b < nb; ++b) {
    const float* xb = x + b * block;
    uint32_t um = 0;
    for (int64_t i = 0; i < block; ++i) {
      uint32_t u;
      std::memcpy(&u, &xb[i], 4);
      u &= 0x7FFFFFFFu;
      um = u > um ? u : um;
    }
    if (um >= 0x7F800000u) return 1;  // inf or nan in this block
    float m;
    std::memcpy(&m, &um, 4);
    // subnormal-absmax blocks flush to zero (mirrors wire.py's
    // _I8_TINY): below this, 1/scale overflows to +inf and the
    // float->int cast of x*inf would be UNDEFINED BEHAVIOR (and for
    // deep subnormals where inv stays finite, scale's own rounding
    // can push |x*inv| past 127). The flush error is < 1.2e-36 —
    // unobservable against either format's quantization step.
    float scale = m < 1.2e-36f ? 0.0f : m * (1.0f / 127.0f);
    float inv = scale > 0.0f ? 1.0f / scale : 0.0f;
    scales[b] = scale;
    int8_t* qb = q + b * block;
    for (int64_t i = 0; i < block; ++i) {
      // round half away from zero: add sign-matched 0.5, truncate.
      // |x*inv| <= 127 by construction, so the int cast never wraps.
      float v = xb[i] * inv;
      uint32_t uv;
      std::memcpy(&uv, &v, 4);
      uint32_t uh = 0x3F000000u | (uv & 0x80000000u);
      float h;
      std::memcpy(&h, &uh, 4);
      qb[i] = (int8_t)(int32_t)(v + h);
    }
  }
  return 0;
}

void rq_dec_i8(const int8_t* q, const float* scales, int64_t block,
               float* out, int64_t n) {
  int64_t nb = n / block;
#if defined(__AVX2__)
  if (aligned32(out) && block % 8 == 0) {
    for (int64_t b = 0; b < nb; ++b) {
      __m256 s = _mm256_set1_ps(scales[b]);
      const int8_t* qb = q + b * block;
      float* ob = out + b * block;
      for (int64_t i = 0; i < block; i += 8)
        _mm256_stream_ps(ob + i, dq8(qb + i, s));
    }
    _mm_sfence();
    return;
  }
#endif
  for (int64_t b = 0; b < nb; ++b) {
    float s = scales[b];
    const int8_t* qb = q + b * block;
    float* ob = out + b * block;
    for (int64_t i = 0; i < block; ++i) ob[i] = (float)qb[i] * s;
  }
}

// fused dequantize-accumulate: acc = src + deq(q) in one pass (the
// ring's reduce step; saves a full 4x-sized temporary write+read over
// decode-then-add)
void rq_dec_add_i8(const int8_t* q, const float* scales, int64_t block,
                   const float* src, float* acc, int64_t n) {
  int64_t nb = n / block;
#if defined(__AVX2__)
  if (aligned32(acc) && block % 8 == 0) {
    for (int64_t b = 0; b < nb; ++b) {
      __m256 s = _mm256_set1_ps(scales[b]);
      const int8_t* qb = q + b * block;
      const float* sb = src + b * block;
      float* ab = acc + b * block;
      for (int64_t i = 0; i < block; i += 8)
        _mm256_stream_ps(
            ab + i, _mm256_add_ps(_mm256_loadu_ps(sb + i),
                                  dq8(qb + i, s)));
    }
    _mm_sfence();
    return;
  }
#endif
  for (int64_t b = 0; b < nb; ++b) {
    float s = scales[b];
    const int8_t* qb = q + b * block;
    const float* sb = src + b * block;
    float* ab = acc + b * block;
    for (int64_t i = 0; i < block; ++i)
      ab[i] = sb[i] + (float)qb[i] * s;
  }
}

// fused both-quantized add: acc = deq(qa) + deq(qb) in one pass (the
// 2-member pairwise exchange, where BOTH contributions ride the wire
// quantized so every rank decodes identical bytes)
void rq_add_qq_i8(const int8_t* qa, const float* sa,
                  const int8_t* qb, const float* sb, int64_t block,
                  float* acc, int64_t n) {
  int64_t nb = n / block;
#if defined(__AVX2__)
  if (aligned32(acc) && block % 8 == 0) {
    for (int64_t b = 0; b < nb; ++b) {
      __m256 fa = _mm256_set1_ps(sa[b]);
      __m256 fb = _mm256_set1_ps(sb[b]);
      const int8_t* ab = qa + b * block;
      const int8_t* bb = qb + b * block;
      float* ob = acc + b * block;
      for (int64_t i = 0; i < block; i += 8)
        _mm256_stream_ps(ob + i,
                         _mm256_add_ps(dq8(ab + i, fa), dq8(bb + i, fb)));
    }
    _mm_sfence();
    return;
  }
#endif
  for (int64_t b = 0; b < nb; ++b) {
    float fa = sa[b], fb = sb[b];
    const int8_t* ab = qa + b * block;
    const int8_t* bb = qb + b * block;
    float* ob = acc + b * block;
    for (int64_t i = 0; i < block; ++i)
      ob[i] = (float)ab[i] * fa + (float)bb[i] * fb;
  }
}

// ---------------------------------------------------------------- bf16

void rq_enc_bf16(const uint32_t* u, int64_t n, uint16_t* q) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t v = u[i];
    uint32_t naninf = (uint32_t)((v & 0x7F800000u) == 0x7F800000u);
    uint32_t hasmant = (uint32_t)((v & 0x007FFFFFu) != 0u);
    uint32_t rounded = (v + (((v >> 16) & 1u) + 0x7FFFu)) >> 16;
    uint32_t trunc = (v >> 16) | ((naninf & hasmant) << 6);
    q[i] = (uint16_t)(naninf ? trunc : rounded);
  }
}

void rq_dec_bf16(const uint16_t* q, int64_t n, uint32_t* out) {
  int64_t i = 0;
#if defined(__AVX2__)
  if (aligned32(out)) {
    for (; i + 8 <= n; i += 8)
      _mm256_stream_ps((float*)(out + i),
                       dqbf16(q + i));
    _mm_sfence();
  }
#endif
  for (; i < n; ++i) out[i] = ((uint32_t)q[i]) << 16;
}

void rq_dec_add_bf16(const uint16_t* q, const float* src, float* acc,
                     int64_t n) {
  int64_t i = 0;
#if defined(__AVX2__)
  if (aligned32(acc)) {
    for (; i + 8 <= n; i += 8)
      _mm256_stream_ps(
          acc + i, _mm256_add_ps(_mm256_loadu_ps(src + i),
                                 dqbf16(q + i)));
    _mm_sfence();
  }
#endif
  for (; i < n; ++i) {
    uint32_t u = ((uint32_t)q[i]) << 16;
    float f;
    std::memcpy(&f, &u, 4);
    acc[i] = src[i] + f;
  }
}

void rq_add_qq_bf16(const uint16_t* qa, const uint16_t* qb, float* acc,
                    int64_t n) {
  int64_t i = 0;
#if defined(__AVX2__)
  if (aligned32(acc)) {
    for (; i + 8 <= n; i += 8)
      _mm256_stream_ps(acc + i,
                       _mm256_add_ps(dqbf16(qa + i), dqbf16(qb + i)));
    _mm_sfence();
  }
#endif
  for (; i < n; ++i) {
    uint32_t ua = ((uint32_t)qa[i]) << 16;
    uint32_t ub = ((uint32_t)qb[i]) << 16;
    float fa, fb;
    std::memcpy(&fa, &ua, 4);
    std::memcpy(&fb, &ub, 4);
    acc[i] = fa + fb;
  }
}

}  // extern "C"
